"""Multi-device SPMD training over a jax.sharding.Mesh, with checkpointing.

Shards entities over every visible device, trains with the all_gather
exchange, checkpoints each iteration, then resumes from the checkpoint to
show crash recovery. Run on real chips as-is, or simulate an 8-device mesh
on CPU:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/sharded_training.py

(If the environment force-registers a TPU platform, the in-process override
below handles CPU forcing — pass --cpu.)

Multi-host (one process per host over DCN) uses the same code path after
``cfk_tpu.parallel.mesh.initialize_distributed()`` +
``make_multihost_mesh()``; see ARCHITECTURE.md §SPMD.
"""

import sys
import tempfile

if "--cpu" in sys.argv:
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax

from cfk_tpu import ALSConfig, parse_netflix
from cfk_tpu.data.blocks import Dataset
from cfk_tpu.eval.metrics import mse_rmse_from_blocks
from cfk_tpu.parallel.mesh import make_mesh
from cfk_tpu.parallel.spmd import train_als_sharded
from cfk_tpu.transport.checkpoint import CheckpointManager


def main() -> None:
    n = len(jax.devices())
    path = "/root/reference/data/data_sample_tiny.txt"
    dataset = Dataset.from_coo(parse_netflix(path), num_shards=n)
    config = ALSConfig(rank=5, lam=0.05, num_iterations=7, seed=0, num_shards=n)
    mesh = make_mesh(n)

    ckdir = tempfile.mkdtemp(prefix="cfk-ck-")
    model = train_als_sharded(
        dataset, config, mesh, checkpoint_manager=CheckpointManager(ckdir)
    )
    mse, rmse = mse_rmse_from_blocks(model.predict_dense(), dataset)
    print(f"{n}-way sharded: MSE={mse:.4f} RMSE={rmse:.4f}")

    # "Crash" and resume: a fresh trainer picks up the final checkpoint and
    # has nothing left to do — factors match the uninterrupted run exactly.
    resumed = train_als_sharded(
        dataset, config, mesh, checkpoint_manager=CheckpointManager(ckdir)
    )
    mse2, rmse2 = mse_rmse_from_blocks(resumed.predict_dense(), dataset)
    assert abs(mse - mse2) < 1e-9
    print(f"resumed from {ckdir}: identical (MSE={mse2:.4f})")


if __name__ == "__main__":
    main()
