"""Explicit ALS-WR end to end: parse → train → evaluate → recommend.

Runs the reference's tiny Netflix sample at its published configuration
(rank 5, 7 iterations, λ=0.05 — `/root/reference/README.md:207`) and prints
MSE/RMSE plus top-5 recommendations for one user.

    python examples/quickstart_explicit.py [RATINGS_FILE]

Use ``--platform cpu``-style forcing by setting it in code (see below) when
no TPU is attached.
"""

import sys

from cfk_tpu import ALSConfig, parse_netflix
from cfk_tpu.data.blocks import Dataset
from cfk_tpu.eval.metrics import mse_rmse_from_blocks
from cfk_tpu.models.als import train_als


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else (
        "/root/reference/data/data_sample_tiny.txt"
    )
    coo = parse_netflix(path)
    dataset = Dataset.from_coo(coo)
    config = ALSConfig(rank=5, lam=0.05, num_iterations=7, seed=0)
    model = train_als(dataset, config)

    mse, rmse = mse_rmse_from_blocks(model.predict_dense(), dataset)
    print(f"train MSE={mse:.4f} RMSE={rmse:.4f}")

    scores, rows = model.recommend_top_k([0], k=5, dataset=dataset)
    movie_ids = [int(dataset.movie_map.raw_ids[r]) for r in rows[0]]
    user_id = int(dataset.user_map.raw_ids[0])
    print(f"top-5 for user {user_id}: movies {movie_ids}")


if __name__ == "__main__":
    main()
