"""Implicit-feedback iALS with ranking evaluation, plus the iALS++ optimizer.

Treats ratings as interaction strengths (Hu et al. 2008 confidence
weighting), holds one interaction per user out, and reports Recall@10 and
mean percentile rank — the evaluation protocol explicit MSE can't provide.
Then retrains with the iALS++ subspace optimizer (same API, ~5× cheaper per
epoch at large rank).

    python examples/quickstart_implicit.py [RATINGS_FILE]
"""

import dataclasses
import sys

from cfk_tpu.data.blocks import Dataset
from cfk_tpu.data.netflix import parse_netflix
from cfk_tpu.eval.ranking import (
    leave_one_out_split,
    mean_percentile_rank,
    recall_at_k,
)
from cfk_tpu.models.ials import IALSConfig, train_ials


def evaluate(model, train_coo, heldout):
    scores = model.predict_dense()
    return (
        recall_at_k(scores, train_coo, heldout, k=10),
        mean_percentile_rank(scores, train_coo, heldout),
    )


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else (
        "/root/reference/data/data_sample_tiny.txt"
    )
    dcoo = Dataset.from_coo(parse_netflix(path)).coo_dense
    train_coo, heldout = leave_one_out_split(
        dcoo.movie_raw, dcoo.user_raw, dcoo.rating, seed=0
    )
    dataset = Dataset.from_coo(train_coo)

    config = IALSConfig(rank=16, lam=0.1, alpha=2.0, num_iterations=8, seed=0)
    recall, mpr = evaluate(train_ials(dataset, config), train_coo, heldout)
    print(f"iALS   : Recall@10={recall:.3f}  MPR={mpr:.3f}")

    pp = dataclasses.replace(config, algorithm="ials++", block_size=4)
    recall, mpr = evaluate(train_ials(dataset, pp), train_coo, heldout)
    print(f"iALS++ : Recall@10={recall:.3f}  MPR={mpr:.3f}")


if __name__ == "__main__":
    main()
