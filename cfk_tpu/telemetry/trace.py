"""Hierarchical span tracing: a low-overhead, thread-aware host tracer.

The reference's entire timeline story was wall-clock ``println`` stamps at
phase edges; ``utils.metrics.Metrics.phase`` improved that to *accumulated*
seconds per phase name — good for bench rows, useless for questions of
SHAPE: does the staging pool's host gather actually run under the consuming
shard's compute?  Which ring visit straggles?  Where does a serve batch's
latency go between assembly, kernel, and respond?  Those are timeline
questions, and this module answers them the way ALX-style systems do: with
a per-thread span timeline exported as Chrome-trace/Perfetto JSON, written
next to the ``maybe_profile`` jax-profiler trace so the host and device
timelines can be read side by side (pass the same ``--trace-dir``).

Design constraints (the sentinel discipline, ISSUE 3's ≤2% budget):

- **Off is near-free and bit-identical.**  No tracer installed ⇒
  ``span()`` returns a module-level null context manager: one global read
  and one function call, no allocation.  Tracing never touches device
  values, so on/off factors are crc-identical by construction (pinned by
  ``chaos_lab telemetry_overhead``).
- **Thread-aware.**  Every event records its OS thread; staging-pool
  worker spans carry the (shard, window) ids their task staged, so pool
  overlap is *visible* in the trace instead of inferred from counters.
- **Async edges.**  ``begin()``/``end()`` return/consume an explicit
  token for spans whose begin and end live on different code paths (or
  different threads); they bypass the per-thread nesting stack.

Span naming: callers pass the FULL taxonomy path (``train/iter/half_step/
window_stage``) — explicit at the call site, zero path-joining overhead
in the hot path.  The taxonomy is documented in ARCHITECTURE.md
("Telemetry").
"""

from __future__ import annotations

import json
import os
import threading
import time

# Hard cap on buffered events: a runaway loop must degrade to dropped
# events (counted), never to unbounded memory.
MAX_EVENTS = 1_000_000


class _NullSpan:
    """The telemetry-off fast path: a reusable no-op context manager."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class SpanToken:
    """An open span's identity for the explicit begin/end (async) API."""

    __slots__ = ("name", "attrs", "t0_us", "tid", "closed")

    def __init__(self, name: str, attrs: dict, t0_us: int, tid: int) -> None:
        self.name = name
        self.attrs = attrs
        self.t0_us = t0_us
        self.tid = tid
        self.closed = False


class _SpanCM:
    """One with-block span; allocated per use (only when tracing is ON)."""

    __slots__ = ("_tracer", "_name", "_attrs", "_t0")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict) -> None:
        self._tracer = tracer
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> "_SpanCM":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter_ns()
        if exc_type is not None:
            # annotate, never swallow — a span that died mid-fault is
            # exactly the event a flight-recorder reader wants labelled
            self._attrs = dict(self._attrs, error=exc_type.__name__)
        # ts and dur both derive from the μs-truncated endpoints (not
        # dur = (t1-t0)//1000): truncating the difference independently
        # can make a child span's end exceed its parent's by 1μs, which
        # would read as a malformed tree.
        ts = self._t0 // 1000
        self._tracer._emit(
            self._name, ts, t1 // 1000 - ts,
            threading.get_ident(), self._attrs,
        )
        return False


class Tracer:
    """Collect host spans; export Chrome-trace JSON.

    Events are appended to one shared list under a small lock (append is
    tens of nanoseconds; span granularity here is per-iteration /
    per-window / per-batch, so contention is negligible against the ≤2%
    budget).  Nesting needs no bookkeeping: with-block spans close in
    LIFO order per thread and ts/dur derive from shared µs-truncated
    endpoints, so the exported tree's well-formedness is checkable from
    the events alone (``validate_span_tree``)."""

    def __init__(self, trace_dir: str | None = None) -> None:
        self.trace_dir = trace_dir
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._thread_names: dict[int, str] = {}
        self.dropped = 0
        self.begin_count = 0
        self.end_count = 0

    # -- recording -----------------------------------------------------------

    def _append(self, event: dict) -> None:
        """One locked append with the cap + thread-name bookkeeping —
        shared by complete spans and instant markers so the drop
        accounting can never diverge between them."""
        with self._lock:
            if len(self._events) >= MAX_EVENTS:
                self.dropped += 1
                return
            tid = event["tid"]
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            self._events.append(event)

    def _emit(self, name: str, ts_us: int, dur_us: int, tid: int,
              attrs: dict) -> None:
        self._append({
            "name": name, "ph": "X", "ts": ts_us, "dur": dur_us,
            "pid": os.getpid(), "tid": tid, "args": attrs,
        })

    def span(self, name: str, **attrs) -> _SpanCM:
        return _SpanCM(self, name, attrs)

    def begin(self, name: str, **attrs) -> SpanToken:
        """Open an async-edge span (end may happen on another thread)."""
        tid = threading.get_ident()
        with self._lock:
            self.begin_count += 1
            # Register the BEGIN thread's name now: end() may run on a
            # different thread, and the event lands on this tid's row —
            # deferring the mapping would mislabel it with the closer's
            # name if no other span emits from this thread first.
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
        return SpanToken(name, attrs, time.perf_counter_ns() // 1000, tid)

    def end(self, token: SpanToken, **extra) -> None:
        """Close an async-edge span; extra attrs merge over begin's.
        Idempotent, including against concurrent double-ends (the
        check-and-set happens under the tracer lock)."""
        with self._lock:
            if token.closed:
                return
            token.closed = True
            self.end_count += 1
        t1 = time.perf_counter_ns() // 1000
        attrs = dict(token.attrs, **extra) if extra else token.attrs
        # attributed to the BEGINNING thread's row (the async span's
        # home); the closing thread is recorded for forensics
        if threading.get_ident() != token.tid:
            attrs = dict(attrs, end_thread=threading.current_thread().name)
        self._emit(token.name, token.t0_us, max(t1 - token.t0_us, 0),
                   token.tid, attrs)

    def instant(self, name: str, **attrs) -> None:
        """A zero-duration marker event."""
        self._append({
            "name": name, "ph": "i", "s": "t",
            "ts": time.perf_counter_ns() // 1000,
            "pid": os.getpid(), "tid": threading.get_ident(),
            "args": attrs,
        })

    # -- export --------------------------------------------------------------

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def chrome_trace(self) -> dict:
        """The Chrome-trace JSON object (Perfetto / chrome://tracing)."""
        events = self.events()
        with self._lock:
            names = dict(self._thread_names)
        meta = [
            {
                "name": "thread_name", "ph": "M", "pid": os.getpid(),
                "tid": tid, "args": {"name": tname},
            }
            for tid, tname in sorted(names.items())
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def write(self, path: str | None = None) -> str | None:
        """Atomically write the Chrome trace; returns the path (None when
        no directory is configured and no path given)."""
        if path is None:
            if self.trace_dir is None:
                return None
            os.makedirs(self.trace_dir, exist_ok=True)
            path = os.path.join(
                self.trace_dir, f"cfk_host_trace_{os.getpid()}.json"
            )
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
        os.replace(tmp, path)
        return path


# -- module-level singleton + fast-path API ----------------------------------

_TRACER: Tracer | None = None


def configure(trace_dir: str | None = None) -> Tracer:
    """Install (and return) the process tracer.  Until this is called,
    every ``span()`` is the null fast path."""
    global _TRACER
    _TRACER = Tracer(trace_dir=trace_dir)
    return _TRACER


def get_tracer() -> Tracer | None:
    return _TRACER


def shutdown(write: bool = True) -> str | None:
    """Uninstall the tracer; optionally write its trace first."""
    global _TRACER
    t = _TRACER
    _TRACER = None
    if t is not None and write:
        return t.write()
    return None


def span(name: str, **attrs):
    """A span context manager — the null singleton when tracing is off."""
    t = _TRACER
    if t is None:
        return _NULL_SPAN
    return t.span(name, **attrs)


def begin_span(name: str, **attrs) -> SpanToken | None:
    t = _TRACER
    if t is None:
        return None
    return t.begin(name, **attrs)


def end_span(token: SpanToken | None, **extra) -> None:
    t = _TRACER
    if t is not None and token is not None:
        t.end(token, **extra)


def instant(name: str, **attrs) -> None:
    t = _TRACER
    if t is not None:
        t.instant(name, **attrs)


# -- analysis helpers --------------------------------------------------------

def validate_span_tree(events: list[dict]) -> dict[int, int]:
    """Check the exported complete-span events form a well-formed tree per
    thread: within one tid, spans either nest or are disjoint (the
    property the per-thread enter/exit stack guarantees — a torn pair
    shows up here as an overlap that is not containment).  Returns
    {tid: span_count}; raises ValueError naming the first violation."""
    by_tid: dict[int, list[dict]] = {}
    for e in events:
        if e.get("ph") == "X":
            by_tid.setdefault(e["tid"], []).append(e)
    counts: dict[int, int] = {}
    for tid, evs in by_tid.items():
        counts[tid] = len(evs)
        evs = sorted(evs, key=lambda e: (e["ts"], -e["dur"]))
        stack: list[tuple[int, int, str]] = []  # (start, end, name)
        for e in evs:
            s, d = e["ts"], e["ts"] + e["dur"]
            while stack and s >= stack[-1][1]:
                stack.pop()
            if stack and d > stack[-1][1]:
                raise ValueError(
                    f"tid {tid}: span {e['name']!r} [{s}, {d}] overlaps "
                    f"but does not nest inside {stack[-1][2]!r} "
                    f"[{stack[-1][0]}, {stack[-1][1]}]"
                )
            stack.append((s, d, e["name"]))
    return counts


def stage_overlap_from_events(events: list[dict]) -> float | None:
    """Recompute the staging engine's ``overlap_hidden_fraction`` from
    trace spans alone: 1 − (consumer wait)/(worker busy), where busy is
    the summed duration of ``window_stage`` spans and wait the summed
    duration of ``window_wait`` spans — the same two intervals
    ``offload/staging.py`` meters into ``stage_busy_s``/``stage_stall_s``,
    measured independently by the tracer.  The acceptance check: this
    number agrees with the driver's own ``offload_stage_hidden_frac``
    gauge within 5%.  Returns None when no staging spans are present."""
    busy = sum(e["dur"] for e in events
               if e.get("ph") == "X" and e["name"].endswith("window_stage"))
    stall = sum(e["dur"] for e in events
                if e.get("ph") == "X" and e["name"].endswith("window_wait"))
    if busy <= 0:
        return None
    return max(0.0, 1.0 - stall / busy)
