"""Live metrics export: Prometheus text rendering + the /metrics endpoint.

The serve server and ``cfk_tpu stream`` answer ``GET /metrics`` with the
registry rendered in the Prometheus text exposition format (0.0.4) — the
unifying naming scheme for what were scattered ad-hoc gauges:

- counters  → ``cfk_<name>_total`` (TYPE counter)
- gauges    → ``cfk_<name>``       (TYPE gauge)
- phases    → ``cfk_phase_seconds{phase="<name>"}`` (TYPE gauge)
- histograms→ ``cfk_<name>{quantile="..."}`` + ``_sum``/``_count``
              (TYPE summary — the bounded-reservoir latency histograms)

Free-text notes are deliberately not exported (they are diagnostics, not
time series; they stay in the JSON line / flight dumps).

``MetricsHTTPServer`` is a ThreadingHTTPServer on its own daemon thread:
requests snapshot the registry under its lock, so scraping under load
reads a consistent view while worker threads keep mutating.
"""

from __future__ import annotations

import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from cfk_tpu.telemetry.metrics import Metrics

PREFIX = "cfk"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_FIRST_RE = re.compile(r"^[^a-zA-Z_:]")


def sanitize_metric_name(name: str) -> str:
    """Map a registry key onto the Prometheus name charset."""
    name = _NAME_RE.sub("_", name)
    if _FIRST_RE.match(name):
        name = "_" + name
    return name


def _fmt(v: float) -> str:
    f = float(v)
    if f != f:  # NaN
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return repr(f) if f != int(f) else str(int(f))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def prometheus_text(metrics: Metrics, prefix: str = PREFIX,
                    labels: dict[str, object] | None = None) -> str:
    """Render the registry in the text exposition format.  One snapshot
    per call (the registry lock guards each family's copy), TYPE line
    before its samples, trailing newline — the conformance test walks
    these properties line by line.

    ``labels`` attaches constant labels to every counter/gauge sample —
    the fleet attribution seam: a multi-process offload host exports with
    ``labels={"process": jax.process_index()}`` so one scrape target per
    host aggregates cleanly (phase/histogram samples keep their own label
    sets; Prometheus merges per-target constant labels upstream)."""
    lines: list[str] = []
    lbl = ""
    if labels:
        pairs = ",".join(
            f'{sanitize_metric_name(str(k))}="{_escape_label(str(v))}"'
            for k, v in sorted(labels.items())
        )
        lbl = "{" + pairs + "}"
    with metrics._lock:
        counters = sorted(metrics.counters.items())
        gauges = sorted(metrics.gauges.items())
        phases = sorted(metrics.phases.items())
        hists = sorted(metrics.histograms.items())
    for name, value in counters:
        m = f"{prefix}_{sanitize_metric_name(name)}_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m}{lbl} {_fmt(value)}")
    for name, value in gauges:
        try:
            v = _fmt(value)
        except (TypeError, ValueError):
            continue  # non-numeric gauge (provenance strings etc.)
        m = f"{prefix}_{sanitize_metric_name(name)}"
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{lbl} {v}")
    if phases:
        m = f"{prefix}_phase_seconds"
        lines.append(f"# TYPE {m} gauge")
        for name, value in phases:
            lines.append(
                f'{m}{{phase="{_escape_label(name)}"}} {_fmt(value)}'
            )
    for name, h in hists:
        m = f"{prefix}_{sanitize_metric_name(name)}"
        lines.append(f"# TYPE {m} summary")
        snap = h.snapshot()  # ONE consistent instant per family
        if snap["count"]:
            for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                lines.append(f'{m}{{quantile="{q}"}} {_fmt(snap[key])}')
        lines.append(f"{m}_sum {_fmt(snap['sum'] if snap['count'] else 0.0)}")
        lines.append(f"{m}_count {snap['count']}")
    return "\n".join(lines) + "\n"


class MetricsHTTPServer:
    """Serve ``GET /metrics`` (Prometheus text) for a registry.

    ``port=0`` binds an ephemeral port (tests); the bound port is
    ``self.port`` after construction.  ``start()`` runs the accept loop
    on a daemon thread; ``stop()`` shuts it down and releases the
    socket.  Also answers ``GET /healthz`` with ``ok`` (the liveness
    probe a supervisor wants next to the scrape target) and — liveness
    and readiness are DIFFERENT questions (ISSUE 18) — ``GET /readyz``:
    200 only while ``ready_fn()`` is true (an engine that is alive but
    still prewarming or mid-epoch-load must not receive traffic; the
    fleet's rollover gate polls exactly this).  ``ready_fn=None`` means
    always ready (the pre-fleet behavior); a ``ready_fn`` that raises
    reads as NOT ready rather than killing the probe."""

    def __init__(self, metrics: Metrics, *, port: int = 0,
                 host: str = "127.0.0.1",
                 labels: dict[str, object] | None = None,
                 ready_fn=None) -> None:
        self.metrics = metrics
        self.labels = dict(labels) if labels else None
        self.ready_fn = ready_fn
        registry = metrics
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?", 1)[0] == "/metrics":
                    body = prometheus_text(
                        registry, labels=outer.labels
                    ).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    outer.scrapes += 1
                elif self.path.split("?", 1)[0] == "/healthz":
                    body = b"ok\n"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.split("?", 1)[0] == "/readyz":
                    try:
                        ready = (outer.ready_fn is None
                                 or bool(outer.ready_fn()))
                    except Exception:
                        ready = False
                    body = b"ready\n" if ready else b"not ready\n"
                    self.send_response(200 if ready else 503)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()

            def log_message(self, *args):  # silence per-request stderr
                pass

        self.scrapes = 0
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def start(self) -> "MetricsHTTPServer":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="cfk-metrics-http", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "MetricsHTTPServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
