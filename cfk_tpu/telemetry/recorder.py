"""Fault flight recorder: a bounded ring of recent events, dumped on faults.

The recovery machinery (PR 3/5/10/12) already *survives* faults; what it
could not do was explain them after the process is gone — a chaos drill or
a real incident left only whatever ``Metrics.notes`` the survivor printed.
This module is the black box: a bounded in-memory ring buffer of recent
span/counter/health events (deque append — effectively free at the
per-iteration / per-batch granularity the instrumentation uses), dumped
ATOMICALLY to disk the moment something goes wrong:

- a health-sentinel trip / escalation / degrade (``resilience/loop.py``,
  ``offload/windowed.py``),
- a staging-worker error propagating out of ``WindowStager.take()``,
- a quarantined stream batch or stream eviction (``streaming/session.py``),
- a preemption/eviction commit (the resilient loops' eviction paths),
- a stall-watchdog exit and — via ``install_crash_hooks`` — any uncaught
  exception.

Every chaos_lab scenario asserts its dump exists and that the FINAL events
name the injected fault; the dump is the forensic timeline of the N steps
before the trip.

Disk policy: dumps are written only when a dump directory is configured
(``FlightRecorder.configure(dump_dir=...)``, the ``CFK_FLIGHT_DIR`` env
var, or the CLI's ``--trace-dir``/checkpoint-dir wiring) — recording
itself is always on, so the buffer is warm whenever a dump trigger fires,
but an unconfigured library user never finds surprise files in their cwd.
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time

DEFAULT_CAPACITY = 512

_ENV_DIR = "CFK_FLIGHT_DIR"

# configure()'s "argument not passed" sentinel: None is a meaningful
# dump_dir value (disable disk dumps), so absence needs its own marker.
_UNSET = object()


class FlightRecorder:
    """Bounded ring buffer of telemetry events + atomic fault dumps."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 dump_dir: str | None = None) -> None:
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        self._dump_n = 0
        self.dump_dir = dump_dir
        self.dumps: list[str] = []

    def configure(self, *, dump_dir=_UNSET,
                  capacity: int | None = None) -> "FlightRecorder":
        """Reconfigure in place.  ``dump_dir`` is only touched when the
        argument is PASSED (None explicitly disables disk dumps) — a
        capacity-only reconfigure must not silently turn fault dumps
        off."""
        with self._lock:
            if dump_dir is not _UNSET:
                self.dump_dir = dump_dir
            if capacity is not None and capacity != self._buf.maxlen:
                self._buf = collections.deque(self._buf, maxlen=capacity)
        return self

    @property
    def capacity(self) -> int:
        return self._buf.maxlen

    def record(self, kind: str, name: str, **fields) -> None:
        """Append one event.  ``kind`` is the coarse class ("train",
        "stream", "serve", "fault", "signal", "checkpoint", ...); ``name``
        the specific event; fields are free-form JSON-able values."""
        evt = {
            "t": round(time.time(), 6),
            "thread": threading.current_thread().name,
            "kind": kind,
            "name": name,
        }
        if fields:
            evt.update(fields)
        with self._lock:
            evt["seq"] = self._seq
            self._seq += 1
            self._buf.append(evt)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dumps = []
            self._dump_n = 0

    def _resolve_dir(self) -> str | None:
        return self.dump_dir or os.environ.get(_ENV_DIR) or None

    def dump(self, reason: str, path: str | None = None) -> str | None:
        """Atomically dump the ring to disk; returns the path, or None
        when no dump directory is configured (events stay in memory).
        Never raises — the recorder must not turn a survivable fault into
        a crash (I/O errors are swallowed, best-effort by contract)."""
        with self._lock:
            events = list(self._buf)
            self._dump_n += 1
            n = self._dump_n
        payload = {
            "reason": reason,
            "pid": os.getpid(),
            "dumped_at_unix": round(time.time(), 6),
            "num_events": len(events),
            "events": events,
        }
        tmp = None
        try:
            if path is None:
                d = self._resolve_dir()
                if d is None:
                    return None
                os.makedirs(d, exist_ok=True)
                slug = re.sub(r"[^A-Za-z0-9_.-]+", "_", reason)[:64]
                path = os.path.join(
                    d, f"cfk_flight_{os.getpid()}_{n:03d}_{slug}.json"
                )
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                # default=repr: record() accepts free-form fields, and a
                # numpy scalar slipping in must degrade to its repr, not
                # raise TypeError out of a fault handler.
                json.dump(payload, f, default=repr)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception:
            # "never raises" is the contract: a dump failure must not
            # turn a survivable fault into a crash of the recovery path.
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            return None
        with self._lock:
            self.dumps.append(path)
        return path


# The process singleton: always recording (appends are near-free), dumps
# only where configured.
_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _RECORDER


def record_event(kind: str, name: str, **fields) -> None:
    _RECORDER.record(kind, name, **fields)


def dump_flight(reason: str) -> str | None:
    return _RECORDER.dump(reason)


_HOOKS_INSTALLED = [False]


def install_crash_hooks() -> None:
    """Chain ``sys.excepthook`` so an uncaught exception dumps the ring
    (reason ``crash:<ExcType>``) before the interpreter's default
    handling.  Idempotent; the CLI installs it whenever a dump directory
    is wired."""
    if _HOOKS_INSTALLED[0]:
        return
    _HOOKS_INSTALLED[0] = True
    import sys

    prev = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            _RECORDER.record("fault", "uncaught_exception",
                             error=f"{exc_type.__name__}: {exc}")
            _RECORDER.dump(f"crash:{exc_type.__name__}")
        except Exception:
            pass
        prev(exc_type, exc, tb)

    sys.excepthook = _hook
