"""Thread-safe typed metrics registry: counters, gauges, phases, histograms.

This is the reworked ``utils.metrics.Metrics`` (the legacy module now
re-exports from here).  The original was a process-local bundle of
defaultdicts — fine while every writer lived on one thread, wrong since
PR 12's staging-pool workers and PR 8's serve commit listeners started
mutating ``counters``/``phases`` from worker threads: ``incr``'s
read-modify-write on a plain dict loses counts under contention (the
hammer test in ``tests/test_telemetry.py`` pins the fix).

What changed:

- every mutating method (``incr``/``gauge``/``note``/``phase``/
  ``observe``) and every snapshot (``to_dict``/``json_line``/``logfmt``)
  takes one registry ``RLock``; the dict attributes stay public (the
  bench/lab row builders read them directly) and single-writer direct
  assignment remains safe as before;
- typed **histograms** (``observe``/``histogram``): bounded-reservoir
  latency distributions — count/sum/min/max exact, quantiles from a
  fixed-size uniform reservoir (deterministically seeded per name), so
  recording a million request latencies costs O(reservoir), not O(n).
  These replace the loadgen's unbounded per-request latency lists;
- the registry renders to Prometheus text via ``telemetry.export`` and
  streams to JSONL via ``MetricsEmitter`` — one naming scheme for the
  ad-hoc gauges (``offload_rows_*``, staging stats, serve latencies,
  recovery rungs) that previously only existed in end-of-run JSON.
"""

from __future__ import annotations

import contextlib
import json
import random
import threading
import time
import zlib
from collections import defaultdict

DEFAULT_RESERVOIR = 1024


class Histogram:
    """Bounded-reservoir distribution: exact count/sum/min/max, quantiles
    approximated from a uniform sample of at most ``reservoir`` values
    (exact while ``count <= reservoir``).  Reservoir sampling (Vitter's
    algorithm R) with a per-name-seeded RNG, so two runs observing the
    same sequence produce the same quantiles."""

    __slots__ = ("name", "count", "sum", "min", "max", "_res", "_cap",
                 "_rng", "_lock")

    def __init__(self, name: str,
                 reservoir: int = DEFAULT_RESERVOIR) -> None:
        if reservoir < 1:
            raise ValueError(f"reservoir must be >= 1, got {reservoir}")
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._res: list[float] = []
        self._cap = int(reservoir)
        self._rng = random.Random(zlib.crc32(name.encode()))
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            if len(self._res) < self._cap:
                self._res.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self._cap:
                    self._res[j] = v

    def reservoir(self) -> list[float]:
        with self._lock:
            return list(self._res)

    @staticmethod
    def _quantile_of(vals: list[float], q: float) -> float:
        """Linear-interpolated quantile of a SORTED list — the same
        estimator as ``np.percentile(..., q*100)``."""
        if not vals:
            return float("nan")
        pos = q * (len(vals) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(vals) - 1)
        frac = pos - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac

    def quantile(self, q: float) -> float:
        """Linear-interpolated quantile over the reservoir — the same
        estimator as ``np.percentile(..., q*100)``, so the loadgen's
        quantile contract is unchanged while its memory is O(1)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            vals = sorted(self._res)
        return self._quantile_of(vals, q)

    def snapshot(self) -> dict:
        """One CONSISTENT locked snapshot: the scalar fields and the
        quantiles all describe the same instant (a concurrent scrape can
        never see a count whose sum/reservoir haven't landed)."""
        with self._lock:
            count, total = self.count, self.sum
            mn, mx = self.min, self.max
            vals = sorted(self._res)
        return {
            "count": count, "sum": total, "min": mn, "max": mx,
            "p50": self._quantile_of(vals, 0.5),
            "p90": self._quantile_of(vals, 0.9),
            "p99": self._quantile_of(vals, 0.99),
        }

    def summary(self) -> dict:
        snap = self.snapshot()
        if snap["count"] == 0:
            return {"count": 0}
        return {
            "count": snap["count"],
            **{k: round(snap[k], 6)
               for k in ("sum", "min", "max", "p50", "p90", "p99")},
        }


class Metrics:
    """Thread-safe metrics registry: counters, gauges, phase timers,
    notes, and bounded-reservoir histograms."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.phases: dict[str, float] = defaultdict(float)
        self.notes: dict[str, str] = {}
        self.histograms: dict[str, Histogram] = {}

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def note(self, name: str, text: str) -> None:
        """Free-text diagnostic (health-sentinel trip reasons, escalation
        decisions, degradation notices) — the report channel the resilience
        loop writes so a degraded run's output says *why*."""
        with self._lock:
            self.notes[name] = text

    def histogram(self, name: str,
                  reservoir: int = DEFAULT_RESERVOIR) -> Histogram:
        """The named histogram, created on first use (the instrument's
        own lock serializes observes, so hot paths never hold the
        registry lock while recording)."""
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram(
                    name, reservoir=reservoir
                )
            return h

    def observe(self, name: str, value: float) -> None:
        self.histogram(name).observe(value)

    @contextlib.contextmanager
    def phase(self, name: str):
        """Accumulate wall seconds spent inside the block under ``name``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.phases[name] += dt

    def to_dict(self) -> dict:
        with self._lock:
            d = {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "phase_seconds": {
                    k: round(v, 6) for k, v in self.phases.items()
                },
            }
            if self.notes:
                d["notes"] = dict(self.notes)
            hists = {k: h.summary() for k, h in self.histograms.items()}
        if hists:
            d["histograms"] = hists
        return d

    def json_line(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    def logfmt(self) -> str:
        with self._lock:
            counters = sorted(self.counters.items())
            gauges = sorted(self.gauges.items())
            phases = sorted(self.phases.items())
            notes = sorted(self.notes.items())
            hists = sorted(self.histograms.items())
        parts = []
        for k, v in counters:
            parts.append(f"ctr.{k}={v:g}")
        for k, v in gauges:
            parts.append(f"g.{k}={v:g}")
        for k, v in phases:
            parts.append(f"t.{k}={v:.3f}s")
        for k, h in hists:
            if h.count:
                parts.append(
                    f"h.{k}=p50:{h.quantile(0.5):g}/p99:"
                    f"{h.quantile(0.99):g}/n:{h.count}"
                )
        for k, v in notes:
            parts.append(f"n.{k}={v!r}")
        return " ".join(parts)


# The registry IS the class — alias for call sites that want the typed
# name rather than the legacy one.
MetricsRegistry = Metrics


class MetricsEmitter:
    """Periodic JSONL metrics emitter for training: one snapshot line per
    interval on a daemon thread, plus a final line at ``stop()`` — the
    live counterpart of the end-of-run ``json_line()`` print, so a
    dashboard (or a tail -f) can watch a multi-hour run converge instead
    of learning everything at exit."""

    def __init__(self, metrics: Metrics, path: str,
                 interval_s: float = 10.0) -> None:
        import os

        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        # Create the target directory up front: failing HERE surfaces a
        # path typo at command start, instead of the writer thread dying
        # silently and stop() raising out of the CLI's exit finally.
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self.metrics = metrics
        self.path = path
        self.interval_s = float(interval_s)
        self.lines_written = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _write_line(self, f) -> None:
        line = {"ts": round(time.time(), 3), **self.metrics.to_dict()}
        f.write(json.dumps(line, sort_keys=True) + "\n")
        f.flush()
        self.lines_written += 1

    def _run(self) -> None:
        with open(self.path, "a") as f:
            while not self._stop.wait(self.interval_s):
                self._write_line(f)

    def start(self) -> "MetricsEmitter":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="cfk-metrics-emitter", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the thread and append one final snapshot line."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with open(self.path, "a") as f:
            self._write_line(f)

    def __enter__(self) -> "MetricsEmitter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
