"""Unified telemetry: span tracing, fault flight recorder, metrics export.

Three instruments, one package (ISSUE 14):

- ``trace`` — hierarchical, thread-aware host span tracing exported as
  Chrome-trace/Perfetto JSON (``--trace-dir``; colocate with the
  ``maybe_profile`` jax-profiler trace so host and device timelines line
  up).  Off by default and near-free when off.
- ``recorder`` — the fault flight recorder: a bounded ring of recent
  events dumped atomically on any trip/escalation/eviction/crash, so
  every chaos scenario (and real incident) leaves a forensic timeline.
- ``metrics`` / ``export`` — the thread-safe typed registry (counters,
  gauges, phases, bounded-reservoir histograms; the reworked
  ``utils.metrics.Metrics``), its periodic JSONL emitter, and the
  Prometheus-text ``/metrics`` endpoint the request server and
  ``cfk_tpu stream`` serve.

Telemetry-off is bit-identical and within the ≤2% overhead budget by the
sentinel discipline: nothing here ever touches device values, span/record
calls are no-ops (one global read) when nothing is configured, and
``chaos_lab telemetry_overhead`` + ``perf_lab --telemetry`` pin it.
"""

from cfk_tpu.telemetry.export import (
    MetricsHTTPServer,
    prometheus_text,
    sanitize_metric_name,
)
from cfk_tpu.telemetry.metrics import (
    Histogram,
    Metrics,
    MetricsEmitter,
    MetricsRegistry,
)
from cfk_tpu.telemetry.recorder import (
    FlightRecorder,
    dump_flight,
    get_recorder,
    install_crash_hooks,
    record_event,
)
from cfk_tpu.telemetry.trace import (
    Tracer,
    begin_span,
    configure,
    end_span,
    get_tracer,
    instant,
    shutdown,
    span,
    stage_overlap_from_events,
    validate_span_tree,
)

__all__ = [
    "FlightRecorder",
    "Histogram",
    "Metrics",
    "MetricsEmitter",
    "MetricsHTTPServer",
    "MetricsRegistry",
    "Tracer",
    "begin_span",
    "configure",
    "dump_flight",
    "end_span",
    "get_recorder",
    "get_tracer",
    "install_crash_hooks",
    "instant",
    "prometheus_text",
    "record_event",
    "sanitize_metric_name",
    "shutdown",
    "span",
    "stage_overlap_from_events",
    "validate_span_tree",
]
