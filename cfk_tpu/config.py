"""Typed configuration for the framework.

The reference scatters its configuration across seven positional CLI args
copied into global mutable statics (``apps/ALSApp.java:17-22,41-48``) that are
read from processors and even the wire deserializer
(``serdes/FeatureMessage/FeatureMessageDeserializer.java:33``), plus a separate
shell script with its own copy of the partition count (``setup.sh``).  Here the
whole configuration is one frozen dataclass threaded explicitly.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Literal


_ASYNC_PERMUTE_FLAG = "xla_tpu_enable_async_collective_permute"


def set_async_collective_permute(mode: str) -> None:
    """Force XLA's async collective-permute pass on/off.

    The double-buffered ring schedule only hides its ICI transfers when the
    compiler splits each collective-permute into start/done pairs and lets
    independent compute run in between; this is the escape hatch when that
    pass itself is the suspect (e.g. an A/B against the serial schedule
    that wants the transfer synchronous at the compiler level too).

    The flag travels via ``LIBTPU_INIT_ARGS`` — parsed only when libtpu
    actually initializes a TPU backend, and silently unused everywhere
    else.  It must NOT go through ``XLA_FLAGS``: CPU/GPU-only XLA builds
    treat the TPU-only flag as unknown and ABORT the whole process at
    backend init (``parse_flags_from_env.cc: F Unknown flags`` — measured
    in this container, where libtpu is importable but the CPU backend
    parses the env).  libtpu reads the env at TPU init, so this must run
    BEFORE the first TPU computation — the CLI applies it at trainer
    entry, before the dataset load touches jax; the sharded trainers
    re-apply best-effort.  An existing occurrence of the flag is
    REWRITTEN to the requested value (an explicit on/off must win over
    leftovers from a previous experiment).  Idempotent; "auto" is a no-op
    (the compiler default already schedules collective permutes async on
    current TPU toolchains).
    """
    if mode == "auto":
        return
    if mode not in ("on", "off"):
        raise ValueError(f"unknown async_collective_permute {mode!r}")
    want = f"--{_ASYNC_PERMUTE_FLAG}={'true' if mode == 'on' else 'false'}"
    flags = os.environ.get("LIBTPU_INIT_ARGS", "")
    parts = [p for p in flags.split() if _ASYNC_PERMUTE_FLAG not in p]
    os.environ["LIBTPU_INIT_ARGS"] = " ".join(parts + [want])


def _jax_backend_initialized() -> bool:
    """Best-effort: has any XLA backend already been created?  Uses a
    private jax registry (the only signal there is); unknowable → False."""
    import sys

    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:  # pragma: no cover - jax internals moved
        return False


def apply_overlap_xla_flags(config: "ALSConfig") -> None:
    """``set_async_collective_permute`` from a config (trainer entry).

    The sharded trainers run after a Mesh exists — i.e. after the backend
    initialized and libtpu already parsed LIBTPU_INIT_ARGS — so from there
    an explicit on/off can no longer take effect this process.  The env is
    still written (idempotent; helps forked workers), but a loud warning
    says to apply it earlier (the CLI does, before the dataset load; a
    library user should call ``set_async_collective_permute`` before the
    first jax computation)."""
    if config.async_collective_permute == "auto":
        return
    if _jax_backend_initialized():
        import warnings

        warnings.warn(
            f"async_collective_permute="
            f"{config.async_collective_permute!r} set after the jax "
            "backend initialized: libtpu has already parsed "
            "LIBTPU_INIT_ARGS, so this run keeps the compiler default — "
            "call cfk_tpu.config.set_async_collective_permute(...) before "
            "the first jax computation (the CLI does this) for it to "
            "take effect"
        )
    set_async_collective_permute(config.async_collective_permute)


def enable_compile_cache(cache_dir: str | None) -> str | None:
    """Wire jax's persistent compilation cache at ``cache_dir`` (the
    ``ALSConfig.compile_cache_dir`` / ``--compile-cache-dir`` seam,
    ISSUE 13).  Returns the resolved per-device directory, or None when
    disabled/unsupported.

    Key discipline: the cache lives in a SUBDIRECTORY keyed by the
    device fingerprint (``plan.DeviceSpec.fingerprint()`` — backend,
    device kind, device count: the same key the autotune cache trusts
    measured winners by), so one shared tree never replays an
    executable compiled for different hardware.  The thresholds are
    lowered to cache every program — the fold-in/serve bucket programs
    this exists for compile in milliseconds each but number dozens per
    cold process (the PR 6 re-trace bound, paid again as re-COMPILE on
    every restart).

    Must run BEFORE the first compile to cover it (trainer/session/
    engine entries call this; jax ignores dir changes for programs
    already compiled).  Idempotent; failures (an old jax without the
    config knobs, an unwritable path) degrade to a no-op with a warning
    rather than failing training."""
    if not cache_dir:
        return None
    import os as _os
    import warnings as _warnings

    try:
        import jax as _jax

        from cfk_tpu.plan.spec import DeviceSpec

        sub = _os.path.join(
            cache_dir, DeviceSpec.detect().fingerprint().replace(":", "_")
        )
        _os.makedirs(sub, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", sub)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           0.0)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        try:
            # jax latches "no cache" on the first compile that ran
            # without a dir; reset so the next compile re-initializes
            # against the directory just configured (measured on 0.4.37:
            # without this, a dir set after any compile is ignored with
            # "cache is disabled/not initialized").
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass  # newer jax may not need (or expose) the reset
        return sub
    except Exception as e:  # pragma: no cover - jax/filesystem specific
        _warnings.warn(
            f"persistent compilation cache disabled ({e}); training "
            "continues with cold compiles"
        )
        return None


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    """Hyper-parameters + execution layout for a block-partitioned ALS run.

    Mirrors the reference CLI surface (``apps/ALSAppRunner.java:16-28``):
    NUM_PARTITIONS → ``num_shards``, NUM_FEATURES → ``rank``, LAMBDA → ``lam``,
    NUM_ITERATIONS → ``num_iterations``; NUM_MOVIES/NUM_USERS are derived from
    the data (the reference made users pass them by hand).
    """

    rank: int = 5
    lam: float = 0.05
    num_iterations: int = 7
    num_shards: int = 1
    seed: int = 42

    # Execution knobs (no analog in the reference — TPU-specific).
    # Storage/exchange dtype of the factor matrices: bfloat16 halves HBM and
    # ICI bytes; Gram accumulation and solves always run float32 internally.
    dtype: Literal["float32", "bfloat16"] = "float32"
    # How fixed-side factors travel between shards each half-iteration:
    #   "all_gather" — one all_gather over ICI, every shard sees full factors
    #                  (the all-to-all-join analog; OutBlock dedup comes free).
    #   "ring"       — ppermute ring, shards accumulate partial Gram matrices
    #                  block by block (the block-to-block-join analog; never
    #                  materializes the full fixed-side matrix per device).
    #                  Available for the padded and tiled layouts; tiled ring
    #                  datasets must be built with Dataset.from_coo(...,
    #                  ring=True).  BOTH halves ring — refused when a half's
    #                  per-entity ring accumulator could not fit (many solve
    #                  entities), which is exactly when all_gather is
    #                  strictly better there.
    #   "hier_ring"  — hierarchical ICI-ring-within-DCN-ring (tiled ring
    #                  datasets only, ISSUE 11): shards group into inner
    #                  rings of ``ici_group`` devices that rotate blocks
    #                  over the fast fabric, with ONE outer hop across the
    #                  slow fabric per phase — O·(I−1) ICI transfers and
    #                  O−1 DCN hops instead of a flat ring whose boundary
    #                  edges pay DCN every step.  Same blocks, same
    #                  accumulator structure as "ring"; with one inner
    #                  ring (ici_group == num_shards) the schedule — and
    #                  the factors — are bit-identical to "ring".
    #   "auto"       — per-HALF memory optimum (tiled layout only): ring on
    #                  the half whose fixed table is big and solve entities
    #                  few (movies at Netflix shape: rotate 480k-user blocks
    #                  instead of all_gathering them), all_gather on the
    #                  other (its ring accumulator would dwarf the table it
    #                  saves).  Build the dataset with Dataset.from_coo(...,
    #                  ring="auto").
    exchange: Literal["all_gather", "ring", "hier_ring", "auto"] = (
        "all_gather"
    )
    # Inner-ring size of the hierarchical exchange: devices per ICI
    # domain.  None = auto (jax.local_device_count() when it divides
    # num_shards, else one flat ring).  Must divide num_shards.
    ici_group: int | None = None
    # Communication/compute overlap — the default execution mode for every
    # ring-layout half-iteration and chunk-streaming body: ring steps are
    # double-buffered (the next block's ppermute is issued before the
    # current block's Gram consumes it) and chunk scans prefetch chunk c+1's
    # neighbor-factor gather while chunk c solves (cfk_tpu.ops.pipeline).
    # False pins the serial reference schedule (each phase drains before
    # the next starts) — the measurement baseline of bench.py --overlap-ab.
    # Factors are bit-identical either way (tests/test_overlap.py).
    overlap: bool = True
    # Fused Gram+solve epilogue: solve each chunk's normal equations INSIDE
    # the pallas Gram kernel's VMEM residency (ridge + lane-vectorized
    # elimination on the resident [Ec, k, k] batch), writing back only the
    # solved [Ec, k] factor rows — the split path's per-chunk A-batch HBM
    # write + readback disappears (cfk_tpu/ops/pallas/gram_kernel.py;
    # ARCHITECTURE.md "Fused Gram+solve epilogue").  None = the process
    # default (on wherever legal: pallas gram backend + pallas solver +
    # rank within the fused elimination's cap — LU 128 / GJ 64 — with
    # automatic fallback to the split schedule otherwise).  False pins the
    # split Gram→HBM→solve schedule in the tiled chunk scans (factors
    # bit-exact either way — the split chunk solve keeps the one-pass
    # reg+solve kernel, so only the round-trip toggles; the bench.py
    # --fused-ab baseline) and gates the accum/ring paths' final fused
    # reg+solve pass.  The knob does not reach the segment/bucketed/
    # padded half-steps, whose solves follow the process default
    # (ops.solve.default_fused_epilogue) only.
    fused_epilogue: bool | None = None
    # In-kernel neighbor gather: fuse the per-chunk neighbor-factor gather
    # into the pallas Gram kernels — the fixed factor table stays in
    # HBM/ANY memory and the kernel DMAs each tile's indexed rows straight
    # into its VMEM double buffer, with the weighted (√aw) premultiply and
    # the padding zero row applied in-register, so the materialized [C, k]
    # gathered stream (HBM write + readback) disappears from the tiled
    # stream/dense/accum/ring chunk bodies (cfk_tpu/ops/pallas/gram_kernel
    # ``*_gather_pallas``; ARCHITECTURE.md "In-kernel neighbor gather").
    # None = the process default (on wherever supported: pallas Gram
    # backend + the kernels' SMEM/alignment gates, with automatic fallback
    # to the XLA-gather path otherwise — interpret/old-jax runs use the
    # emulation twin either way).  False pins the XLA-gather schedule (the
    # bench.py --gather-ab baseline).  Factors are bit-identical across
    # the knob (tests/test_in_kernel_gather.py).
    in_kernel_gather: bool | None = None
    # HBM gather-table dtype (cfk_tpu.ops.quant; approximate-computing MF,
    # arXiv 1808.03843): the RAW fixed-side table each half-iteration
    # gathers from is stored "float32" (identity — bit-identical to
    # pre-quantization behavior), "bfloat16" (half the gather bytes), or
    # "int8" (a quarter, plus one f32 scale per row — symmetric per-row
    # quantization, the scale folded into the kernels' premultiply weight
    # so the dequantize rides the existing √aw/mask pass).  Gram/solve
    # accumulation stays float32 in-register for every choice, and the
    # SOLVED (master) factors keep ``dtype`` — this knob only shrinks the
    # gather operand, which is what the bytes-bound gather roofline
    # charges.  int8 needs the per-row scale threaded through a weight
    # stream, which the tiled and bucketed layouts have; padded/segment
    # support float32/bfloat16 only.  Ring exchanges rotate the quantized
    # payload (bf16 on both rings, int8+scale on the tiled ring).
    table_dtype: Literal["float32", "bfloat16", "int8"] = "float32"
    # Elimination algorithm of the fused reg+solve kernels: "lu" (reverse
    # no-pivot LU, rank cap 128) or "gj" (Gauss-Jordan, cap 64); "auto"
    # defers to the process default (ops.pallas.solve_kernel.
    # default_reg_solve_algo — the CFK_REG_SOLVE_ALGO env var / perf_lab
    # --reg-solve-algo patch point).  This is a real threaded parameter
    # (a jit-static on every half-step), which is how the recovery
    # ladder's GJ rung flips it now (cfk_tpu.resilience.policy) — it used
    # to ride the env var.
    reg_solve_algo: Literal["auto", "lu", "gj"] = "auto"
    # Escape hatch for XLA's async collective-permute scheduling on TPU —
    # the compiler pass that actually hides the ring's ppermute behind the
    # double-buffered Gram compute.  "auto" leaves the compiler default
    # (async on current XLA); "on"/"off" force the flag via
    # LIBTPU_INIT_ARGS (``apply_overlap_xla_flags`` — must run before TPU
    # backend init to take effect, which the sharded trainers attempt
    # best-effort; harmless off-TPU, where libtpu never parses it).
    async_collective_permute: Literal["auto", "on", "off"] = "auto"
    # --- HBM bounding: ONE knob ------------------------------------------
    # Every layout bounds the same quantity — the transient neighbor-factor
    # gather feeding the MXU — by streaming solves through HBM in chunks.
    # ``hbm_chunk_elems`` is that budget in gather *cells* (rows × width ≈
    # ratings per chunk) for every layout:
    #   - padded: consumed at solve time — entities per chunk are derived
    #     as ``hbm_chunk_elems // rectangle_width`` (see
    #     ``padded_solve_chunk``);
    #   - bucketed/segment/tiled: consumed at dataset build time — pass it
    #     as ``Dataset.from_coo(..., chunk_elems=cfg.chunk_cells())`` (the
    #     CLI's --chunk-elems does); the chunk hints then live statically
    #     on the blocks.
    # None = layout defaults (padded: whole shard at once; build-time
    # layouts: the 1M-cell default).
    hbm_chunk_elems: int | None = None
    # DEPRECATED alias: entities per padded-layout solve chunk, overriding
    # the derived value.  Use hbm_chunk_elems.
    solve_chunk: int | None = None
    # Batched k×k SPD solve backend: "cholesky" = XLA custom calls;
    # "pallas" = lane-vectorized Gauss-Jordan TPU kernel (cfk_tpu.ops.pallas);
    # "auto" = pallas on TPU for ranks within the kernel's VMEM budget
    # (~1.7× faster end-to-end at full-Netflix scale — XLA's batched
    # cholesky/triangular custom calls are latency-bound at small k),
    # cholesky everywhere else (CPU interpret-mode pallas is test-only slow).
    solver: Literal["auto", "cholesky", "pallas"] = "auto"
    # Pad ragged neighbor lists up to a multiple of this (MXU-friendly tiling).
    # Consumed wherever blocks are built from this config (ring-block builds,
    # CLI/bench dataset construction); pass it to Dataset.from_coo when
    # building datasets by hand.
    pad_multiple: int = 8
    # InBlock memory layout:
    #   "padded"   — one [E, max_nnz] rectangle per side. Simple and fastest
    #                up to medium scale, but pads every entity to the global
    #                max degree — quadratic waste on power-law data.
    #   "bucketed" — power-of-two width classes (the ALX layout); total
    #                padded cells stay within ~2× nnz, required at full
    #                Netflix-Prize scale. all_gather exchange only.
    #   "segment"  — flat CSR-style runs scanned in fixed-size nnz chunks;
    #                Gram matrices accumulate by grouped ragged matmul on the
    #                MXU, and entities hotter than one chunk straddle chunks
    #                via a carried partial Gram. Exactly O(nnz) memory for
    #                arbitrarily skewed degree distributions. all_gather only.
    #   "tiled"    — segment layout with entity runs padded to [T]-row tiles:
    #                Grams become one batched tile GEMM + a tiny segment-sum,
    #                and the few-entity side gathers from dynamic table
    #                slices (the big-table gather cliff). ~2× faster than
    #                "segment" at full-Netflix scale — the at-scale default.
    #                all_gather exchange only.
    layout: Literal["padded", "bucketed", "segment", "tiled"] = "padded"
    # DEPRECATED alias for hbm_chunk_elems (the build-time consumption is
    # described there); retained so round-2 configs keep working.
    bucket_chunk_elems: int = 1 << 20
    # Per-entity optimizer.  "als" = the reference's exact full k×k normal-
    # equation solve every half-iteration.  "als++" = warm-started subspace
    # block coordinate descent (the explicit-feedback analog of iALS++,
    # cfk_tpu/ops/subspace.py): per coordinate block B solve
    # A[B,B]δ = −g[B] with ALS-WR's λ·n·I regularization; with
    # block_size == rank one sweep equals the full solve exactly.  Cheaper
    # per epoch at large rank, but a different per-epoch trajectory — the
    # reference-parity path stays "als".  padded/bucketed layouts only.
    algorithm: str = "als"
    block_size: int = 32
    sweeps: int = 1
    # --- self-healing (cfk_tpu.resilience) -------------------------------
    # Numerical-health sentinel cadence: probe the factor state (isfinite
    # reductions + max-row-norm watchdogs, O(E·k) — measured < 2% s/iter
    # at health_check_every=1 on the bench dense-stream config) every N
    # completed iterations.  None disables the sentinel entirely; the
    # fused single-device loop then stays a pure fori_loop and the stepped
    # loops skip the probe fetch.  Must be >= 1 when set.
    health_check_every: int | None = None
    # Factor-row 2-norm above which the watchdog trips even though every
    # value is still finite — catches the slow blow-up that precedes
    # overflow by several iterations (divergence is cheapest to fix early).
    health_norm_limit: float = 1e6
    # Recovery ladder bounds (cfk_tpu.resilience.policy): total sentinel
    # trips tolerated before the run stops retrying; each trip rolls back
    # to the last good checkpoint and climbs one escalation rung
    # (retry → λ×lam_escalation → split epilogue → GJ elimination — the
    # default of 4 makes the full ladder reachable before degrading).
    max_recoveries: int = 4
    lam_escalation: float = 10.0
    # When retries are exhausted: "degrade" returns the last-good factors
    # with a diagnostic report in the metrics (production default — a
    # stale model beats no model), "raise" raises TrainingDivergedError.
    on_unrecoverable: Literal["degrade", "raise"] = "degrade"
    # --- execution planner (cfk_tpu.plan, ISSUE 9) -----------------------
    # How the trainers resolve their ExecutionPlan.  Every CONCRETE knob
    # above becomes a pinned constraint (plan.constraints_from_config), so
    # the CLI surface is unchanged and the default config's execution is
    # bit-identical across modes; the planner prices the knobs the config
    # left deferred (None/"auto") and records provenance either way.
    #   "model"    — cost-model resolution of the free knobs (default;
    #                today's free knobs are bit-exact across choices).
    #   "pinned"   — no optimization: pins + legacy process defaults (the
    #                pre-planner behavior, still recorded as a plan).
    #   "autotune" — consult the measured-winner cache (warmed offline by
    #                `cfk_tpu plan --autotune` / perf_lab); model fallback
    #                with cache=miss provenance when cold.  Trainers never
    #                measure inline.
    plan: Literal["model", "pinned", "autotune"] = "model"
    # --- out-of-core factor tables (cfk_tpu.offload, ISSUE 11) ----------
    # Where the factor tables live during training:
    #   "auto"        — the planner decides via the memory-budget predicate
    #                   (cfk_tpu.offload.budget): resident while both
    #                   tables + blocks fit the device budget (today's
    #                   behavior, bit-identical), host_window past it.
    #   "device"      — pin HBM-resident tables; the planner REFUSES
    #                   (PlanConstraintError) when the budget predicate
    #                   says they cannot fit, instead of promising an OOM.
    #   "host_window" — pin the out-of-core path: host-RAM factor stores
    #                   with device_put-pipelined windows
    #                   (offload.windowed.train_als_host_window — explicit
    #                   ALS, tiled layout; sharded too, per-shard windows
    #                   under the all_gather scan or the ring/hier_ring
    #                   visit schedules with int8 (codes, scales) PCIe
    #                   staging; bit-exact vs the resident paths).
    offload_tier: Literal["auto", "device", "host_window"] = "auto"
    # --- host staging engine (cfk_tpu.offload.staging, ISSUE 13) --------
    # How the host_window tier's windows are staged (gather + quantize +
    # checksum + device_put):
    #   "auto"/"pool" — ONE bounded thread pool per half-iteration stages
    #                   every shard's windows ahead of consumption, so
    #                   shard d+1's host-side window work overlaps shard
    #                   d's compute (the ALX per-shard transfer pipeline's
    #                   host half; the default, like PR 1's overlap).
    #   "serial"      — the PR 10/11 single-thread double buffer (the
    #                   measurement baseline of bench.py --staging-ab).
    # Factors are crc-identical across the knob (the staging order never
    # changes the consumption order — tests/test_offload_sharded.py).
    staging: Literal["auto", "pool", "serial"] = "auto"
    # Staged-ahead windows beyond the one being consumed (pool mode).
    # None = offload.staging.DEFAULT_POOL_DEPTH; always clamped so
    # depth+1 worst-case windows fit the per-shard window budget next to
    # the ring accumulator reservation (offload.budget.max_pool_depth).
    staging_pool_depth: int | None = None
    # --- skew-aware hot-row device cache (cfk_tpu.offload.hot, ISSUE 15)
    # The host_window tier keeps the top-f fixed-table rows (by cross-
    # window reference count — the power-law head) device-resident at
    # the staging dtype, and windows stage only their COLD DELTA vs the
    # schedule predecessor:
    #   None  — AUTO: f from the coverage-curve knee of the window
    #           plans' own reference counts, clamped by the budget
    #           headroom left after the accumulator/window/delta-arena
    #           reservations (resolves to 0 — off — when headroom or
    #           skew refuses).
    #   0     — OFF: byte-for-byte the PR 12 full-staging engine.
    #   >= 1  — pin the TOTAL resident rows across both sides; an
    #           impossible reservation raises loudly (planner AND
    #           executor, offload.budget.hot_reservation_fits).
    # Factors are crc-identical across the knob (assembled windows are
    # bitwise the fully-staged ones); only staged PCIe bytes change.
    hot_rows: int | None = None
    # --- warm-start compile caching (ISSUE 13) --------------------------
    # Directory for jax's persistent compilation cache.  None disables
    # (today's behavior).  A path is keyed per device fingerprint (the
    # autotune cache's discipline — a winner compiled on one backend
    # must not collide with another's), so one tree serves mixed fleets;
    # trainers/serving/streaming apply it at entry via
    # enable_compile_cache(), BEFORE their first compile.  Cold-process
    # time-to-first-step/batch is what it buys; trace counts are
    # unchanged (tracing is jax-side — the cache removes the XLA compile
    # behind each trace).
    compile_cache_dir: str | None = None
    # --- elastic fleet membership (ISSUE 20) ----------------------------
    # Multi-process host_window training survives a dead peer live: a
    # collective failure triggers the shrink protocol (min-agree the
    # covered step, repartition ownership, reload the orphan slice,
    # continue) instead of the bounded exit, and a restarted host can
    # rejoin at an iteration boundary.  None = AUTO: elastic when a
    # fleet-manifests directory is available (the protocol needs the
    # per-host manifests to agree and reload), off otherwise.
    fleet_elastic: bool | None = None
    # Transient-vs-fatal peer classification: a fleet collective that
    # fails with a retryable error (slow GC pause, dropped packet) is
    # retried with backoff+jitter up to fleet_retry_attempts times
    # before the peer is declared dead and the shrink fires.
    fleet_retry_attempts: int = 2
    fleet_retry_base_s: float = 0.05
    fleet_retry_max_delay_s: float = 1.0
    # A collective that HANGS (no error) is declared dead after this
    # many seconds — SIGKILL'd Gloo peers sometimes hang the survivor
    # rather than erroring.  None disables the timeout (the
    # StallWatchdog remains the outer backstop).
    fleet_collective_timeout_s: float | None = None

    def _valid_algorithms(self) -> tuple[str, ...]:
        return ("als", "als++")

    def _check_host_window(self) -> None:
        """The per-family ``offload_tier='host_window'`` gate.  The
        explicit base family streams the tiled stream-mode layout under
        explicit ALS; ``IALSConfig`` overrides for the bucketed
        width-class windows (ISSUE 19)."""
        if self.layout != "tiled":
            raise ValueError(
                f"offload_tier='host_window' streams the tiled "
                f"stream-mode layout; layout={self.layout!r}"
            )
        if self.algorithm != "als":
            raise ValueError(
                "offload_tier='host_window' supports the explicit ALS "
                f"optimizer at layout='tiled'; algorithm="
                f"{self.algorithm!r} (the subspace als++ windowed walk "
                "is the documented follow-up — the implicit family's "
                "iALS/iALS++ run out-of-core via IALSConfig)"
            )

    def chunk_cells(self) -> int:
        """The gather-cell budget for build-time layouts: the one knob
        (``hbm_chunk_elems``) when set, else the deprecated
        ``bucket_chunk_elems`` (whose default is the historical 1M)."""
        if self.hbm_chunk_elems is not None:
            return self.hbm_chunk_elems
        return self.bucket_chunk_elems

    def padded_solve_chunk(self, width: int) -> int | None:
        """Entities per padded-layout solve chunk under the cell budget.

        The deprecated explicit ``solve_chunk`` (entity units) wins when
        set; otherwise ``hbm_chunk_elems // width`` — the same budget the
        build-time layouts consume, derived for a rectangle ``width``
        columns wide.  None = solve the whole shard at once."""
        if self.solve_chunk is not None:
            return self.solve_chunk
        if self.hbm_chunk_elems is None:
            return None
        return max(1, self.hbm_chunk_elems // max(width, 1))

    def __post_init__(self) -> None:
        if self.async_collective_permute not in ("auto", "on", "off"):
            raise ValueError(
                "unknown async_collective_permute "
                f"{self.async_collective_permute!r}"
            )
        if self.fused_epilogue not in (None, True, False):
            raise ValueError(
                f"fused_epilogue must be None/True/False, got "
                f"{self.fused_epilogue!r}"
            )
        if self.in_kernel_gather not in (None, True, False):
            raise ValueError(
                f"in_kernel_gather must be None/True/False, got "
                f"{self.in_kernel_gather!r}"
            )
        if self.table_dtype not in ("float32", "bfloat16", "int8"):
            raise ValueError(
                f"table_dtype must be 'float32', 'bfloat16' or 'int8', "
                f"got {self.table_dtype!r}"
            )
        if self.table_dtype == "int8" and self.layout not in (
            "tiled", "bucketed"
        ):
            # Mirrors ops.quant.validate_table_dtype_layout (kept inline so
            # config stays importable without jax): int8 needs the per-row
            # dequant scale folded into a weight stream, which only the
            # tiled/bucketed formulations carry.
            raise ValueError(
                f"table_dtype='int8' supports layout='tiled'/'bucketed' "
                f"(the per-row scale rides their weight streams); "
                f"layout={self.layout!r} should use 'bfloat16' or 'float32'"
            )
        if self.reg_solve_algo not in ("auto", "lu", "gj"):
            raise ValueError(
                f"reg_solve_algo must be 'auto', 'lu' or 'gj', got "
                f"{self.reg_solve_algo!r}"
            )
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")
        if self.num_iterations < 1:
            raise ValueError(f"num_iterations must be >= 1, got {self.num_iterations}")
        if self.num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {self.num_shards}")
        if self.lam < 0:
            raise ValueError(f"lam must be >= 0, got {self.lam}")
        if self.exchange not in ("all_gather", "ring", "hier_ring", "auto"):
            raise ValueError(f"unknown exchange {self.exchange!r}")
        if self.exchange == "hier_ring" and self.layout != "tiled":
            raise ValueError(
                f"exchange='hier_ring' is implemented for layout='tiled' "
                f"(the ring-built tiled blocks); layout={self.layout!r}"
            )
        if self.ici_group is not None:
            if self.ici_group < 1:
                raise ValueError(
                    f"ici_group must be >= 1 (devices per inner ring), "
                    f"got {self.ici_group}"
                )
            if self.num_shards % self.ici_group != 0:
                raise ValueError(
                    f"ici_group={self.ici_group} must divide "
                    f"num_shards={self.num_shards} (the outer ring walks "
                    "whole inner rings)"
                )
        if self.offload_tier not in ("auto", "device", "host_window"):
            raise ValueError(
                f"offload_tier must be 'auto', 'device' or 'host_window', "
                f"got {self.offload_tier!r}"
            )
        if self.staging not in ("auto", "pool", "serial"):
            raise ValueError(
                f"staging must be 'auto', 'pool' or 'serial', got "
                f"{self.staging!r}"
            )
        if self.staging_pool_depth is not None and self.staging_pool_depth < 1:
            raise ValueError(
                f"staging_pool_depth must be >= 1 (windows staged ahead "
                f"of consumption), got {self.staging_pool_depth}; use "
                "staging='serial' for the unpooled baseline"
            )
        if self.hot_rows is not None and self.hot_rows < 0:
            raise ValueError(
                f"hot_rows must be None (auto), 0 (off) or a positive "
                f"total resident row count, got {self.hot_rows}"
            )
        if self.offload_tier == "host_window":
            # Family hook: explicit ALS streams the tiled stream-mode
            # layout; the implicit family (IALSConfig) overrides with the
            # bucketed width-class gate (ISSUE 19).  Sharded host_window
            # is supported (ISSUE 12): the windowed driver runs per-shard
            # staged windows under the all_gather scan or the
            # ring/hier_ring visit schedules — no shard-count restriction
            # here; exchange/layout rules below still apply.
            self._check_host_window()
        if self.solver not in ("auto", "cholesky", "pallas"):
            raise ValueError(f"unknown solver {self.solver!r}")
        if self.layout not in ("padded", "bucketed", "segment", "tiled"):
            raise ValueError(f"unknown layout {self.layout!r}")
        if self.layout not in ("padded", "tiled") and self.exchange == "ring":
            raise ValueError(
                f"layout={self.layout!r} supports exchange='all_gather' only"
            )
        if self.exchange == "auto" and self.layout != "tiled":
            raise ValueError(
                "exchange='auto' (per-half ring/all_gather selection) "
                f"applies to layout='tiled'; layout={self.layout!r} should "
                "pick 'all_gather' or 'ring' explicitly"
            )
        if self.health_check_every is not None and self.health_check_every < 1:
            raise ValueError(
                f"health_check_every must be >= 1 (iterations between "
                f"sentinel probes), got {self.health_check_every}; use "
                "health_check_every=None to disable the health sentinel"
            )
        if self.health_norm_limit <= 0:
            raise ValueError(
                f"health_norm_limit must be > 0 (a factor-row 2-norm "
                f"bound), got {self.health_norm_limit}"
            )
        if self.max_recoveries < 0:
            raise ValueError(
                f"max_recoveries must be >= 0, got {self.max_recoveries}"
            )
        if self.fleet_retry_attempts < 0:
            raise ValueError(
                f"fleet_retry_attempts must be >= 0 (retries before a "
                f"peer is declared dead), got {self.fleet_retry_attempts}"
            )
        if self.fleet_retry_base_s <= 0:
            raise ValueError(
                f"fleet_retry_base_s must be > 0, got "
                f"{self.fleet_retry_base_s}"
            )
        if self.fleet_retry_max_delay_s < self.fleet_retry_base_s:
            raise ValueError(
                f"fleet_retry_max_delay_s must be >= fleet_retry_base_s, "
                f"got {self.fleet_retry_max_delay_s} < "
                f"{self.fleet_retry_base_s}"
            )
        if (self.fleet_collective_timeout_s is not None
                and self.fleet_collective_timeout_s <= 0):
            raise ValueError(
                f"fleet_collective_timeout_s must be > 0 (or None to "
                f"disable), got {self.fleet_collective_timeout_s}"
            )
        if self.lam_escalation <= 1:
            raise ValueError(
                f"lam_escalation must be > 1 (it multiplies λ on "
                f"escalation), got {self.lam_escalation}"
            )
        if self.on_unrecoverable not in ("degrade", "raise"):
            raise ValueError(
                f"on_unrecoverable must be 'degrade' or 'raise', got "
                f"{self.on_unrecoverable!r}"
            )
        if self.plan not in ("model", "pinned", "autotune"):
            raise ValueError(
                f"plan must be 'model', 'pinned' or 'autotune', got "
                f"{self.plan!r}"
            )
        if self.hbm_chunk_elems is not None and self.hbm_chunk_elems < 1:
            raise ValueError(
                f"hbm_chunk_elems must be >= 1, got {self.hbm_chunk_elems}"
            )
        if self.layout != "padded" and self.solve_chunk is not None:
            raise ValueError(
                f"solve_chunk (deprecated) applies to layout='padded' "
                f"only; use hbm_chunk_elems — one budget for every layout "
                f"(build-time layouts consume it via Dataset.from_coo(..., "
                "chunk_elems=cfg.chunk_cells()), which the CLI's "
                "--chunk-elems does)"
            )
        if self.algorithm not in self._valid_algorithms():
            raise ValueError(
                f"unknown algorithm {self.algorithm!r} for "
                f"{type(self).__name__}; valid: {self._valid_algorithms()}"
            )
        if self.algorithm != "als":
            if self.layout in ("segment", "tiled"):
                raise ValueError(
                    f"{self.algorithm} supports the padded and bucketed "
                    f"layouts (bucketed is the at-scale one); the "
                    f"{self.layout} layout's chunk-straddling entities "
                    "would need cross-chunk score updates — use "
                    "layout='bucketed'"
                )
            if self.rank % self.block_size != 0:
                raise ValueError(
                    f"rank {self.rank} not divisible by block_size "
                    f"{self.block_size}"
                )
            if self.sweeps < 1:
                raise ValueError(f"sweeps must be >= 1, got {self.sweeps}")
            if self.exchange != "all_gather":
                raise ValueError(
                    f"{self.algorithm} supports exchange='all_gather' only"
                )
            if self.solve_chunk is not None:
                raise ValueError(
                    f"solve_chunk is not honored by {self.algorithm} (the "
                    "subspace sweep has no entity-chunked padded path); use "
                    "layout='bucketed' with chunk_elems to bound HBM"
                )
