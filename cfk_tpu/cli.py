"""Command-line interface.

Subcommands:

- ``run`` — reference-compatible positional form, mirroring
  ``apps/ALSAppRunner.java:16-28`` / README.md:35 of the reference:
  ``NUM_PARTITIONS NUM_FEATURES LAMBDA NUM_ITERATIONS PATH NUM_MOVIES
  NUM_USERS``.  Entity counts are *derived from the data* here; the passed
  NUM_MOVIES/NUM_USERS are cross-checked and warned about on mismatch
  (the reference trusts them blindly and mis-sizes its collector if wrong).
- ``train`` — full-flag form: explicit or implicit model, sharding,
  exchange strategy, solver backend, checkpointing, profiling.
- ``evaluate`` — offline MSE/RMSE of a prediction CSV against a ratings
  file: the (fixed) replacement for ``scripts/calculate_mse.py`` (which
  reads uninitialized ``np.empty`` memory and can print nan).
- ``recommend`` — top-K serving from checkpointed factors.
- ``predict`` — prediction-CSV dump from checkpointed factors (the
  reference's final-collection phase as a standalone step).
- ``broker`` / ``produce`` — run the native TCP log broker and stream a
  ratings file into it; ``train --data tcp://HOST:PORT[/TOPIC]`` then
  ingests from the broker (the reference's producer → Kafka → app split,
  ``apps/ALSAppRunner.java:30-33``, as separate processes).
- ``stream`` — exactly-once streaming fold-in: consume rating updates
  from a durable topic and fold them into live factors, committing
  factors + offset cursor atomically per micro-batch; ``--produce-csv``
  is the producer side (``cfk_tpu.streaming``; ARCHITECTURE.md
  "Streaming ingest & incremental fold-in").
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def _eprint(*args) -> None:
    print(*args, file=sys.stderr)


def _parse_tcp_url(url: str, topic_optional: bool = False) -> tuple[str, int, str]:
    """``tcp://HOST:PORT[/TOPIC]`` → (host, port, topic).

    Without a /TOPIC segment the default ratings topic is returned, or None
    when ``topic_optional`` (admin commands that act on the whole broker).
    """
    from cfk_tpu.transport.ingest import RATINGS_TOPIC

    if not url.startswith("tcp://"):
        raise ValueError(
            f"bad broker url {url!r}; expected tcp://HOST:PORT[/TOPIC]"
        )
    rest = url[len("tcp://"):]
    addr, _, topic = rest.partition("/")
    host, _, port_s = addr.rpartition(":")
    if not host or not port_s.isdigit():
        raise ValueError(f"bad broker url {url!r}; expected tcp://HOST:PORT[/TOPIC]")
    return host, int(port_s), topic or (None if topic_optional else RATINGS_TOPIC)


AUTO_LAYOUT_TILED_NNZ = 2_000_000  # above this, tiled wins (BASELINE.md)


def _resolve_auto_layout(coo, algorithm="als", solve_chunk=None) -> str:
    """layout='auto': one padded rectangle for small data (fastest to
    compile, no chunking machinery), the tiled layout once the data is
    big enough for its batched-GEMM Grams to matter.  Constrained by the
    rest of the invocation: an explicit (deprecated) --solve-chunk only
    means anything on the padded layout, and the subspace optimizers
    (als++/ials++) need padded/bucketed — bucketed is their at-scale
    layout (what bench.py's subspace path uses)."""
    if solve_chunk is not None:
        return "padded"
    big = coo.num_ratings >= AUTO_LAYOUT_TILED_NNZ
    if algorithm != "als":
        return "bucketed" if big else "padded"
    return "tiled" if big else "padded"


def _load_dataset(path, fmt, min_rating, num_shards, pad_multiple, layout="padded",
                  chunk_elems=1 << 20, cache_dir=None, ring=False,
                  auto_resolver=_resolve_auto_layout, auto_key=None,
                  dense_stream=False):
    import os

    from cfk_tpu.data.blocks import Dataset
    from cfk_tpu.data.movielens import parse_movielens_csv
    from cfk_tpu.data.netflix import parse_netflix

    import zipfile

    # Built blocks are deterministic for this tuple; it is stored in the
    # cache's meta.json so a cache built from other data or flags is
    # rebuilt instead of silently reused.  Content fingerprint: size + mtime
    # for files, per-partition end offsets for broker topics (append-only
    # logs — the offsets identify the ingested prefix exactly).
    build_key = {
        "data": path if path.startswith("tcp://") else os.path.abspath(path),
        "format": fmt,
        "min_rating": min_rating,
        "num_shards": num_shards,
        "pad_multiple": pad_multiple,
        "layout": layout,
        "chunk_elems": chunk_elems,
    }
    if ring:  # absent for non-ring keys so existing caches stay valid
        build_key["ring"] = ring
    if dense_stream and layout == "tiled":
        # Same back-compat rule — and only for layouts that can actually
        # consume the flag: recording it for explicit padded/bucketed/
        # segment builds would spuriously invalidate their caches while
        # producing byte-identical blocks.
        build_key["dense_stream"] = True
    if layout == "auto" and auto_key:
        # layout='auto' resolves from the data AND the invocation
        # (algorithm, solve_chunk constrain the choice) — without these in
        # the key, a cache built under `als` would be silently reused by
        # `ials++` with a layout that invocation cannot train on.
        build_key.update(auto_key)

    # For layout='auto' the dense flag only changes the blocks when the
    # resolution lands on tiled — unknowable before the data is parsed, so
    # the flag cannot be keyed up front (keying it on the UNRESOLVED layout
    # spuriously invalidated pre-existing auto caches whose resolution was
    # segment/bucketed — ADVICE r4).  Saves record the flag iff the
    # resolved build consumed it; loads accept the flagless key too, but
    # only when the cached dataset is NOT tiled (a flagless tiled cache is
    # a padded build and must not serve a dense request).
    auto_dense = dense_stream and layout == "auto"

    def cache_or_build(build):
        if cache_dir and os.path.exists(os.path.join(cache_dir, "meta.json")):
            keys = ([{**build_key, "dense_stream": True}, build_key]
                    if auto_dense else [build_key])
            err = None
            for key in keys:
                try:
                    ds = Dataset.load(cache_dir, expect_build_key=key)
                except (ValueError, KeyError, OSError,
                        zipfile.BadZipFile) as e:
                    # mismatched build key, or a missing/corrupt/truncated
                    # cache file: every broken-cache state self-heals via
                    # rebuild
                    err = e
                    continue
                from cfk_tpu.data.blocks import TiledBlocks

                if (auto_dense and "dense_stream" not in key
                        and isinstance(ds.user_blocks, TiledBlocks)):
                    err = ValueError(
                        "cached auto-layout dataset resolved to tiled "
                        "without the dense stream; dense run rebuilds"
                    )
                    continue
                return ds
            _eprint(f"warning: ignoring dataset cache: {err}")
        coo = build()
        resolved = auto_resolver(coo) if layout == "auto" else layout
        use_dense = dense_stream and resolved == "tiled"
        ds = Dataset.from_coo(
            coo, num_shards=num_shards, pad_multiple=pad_multiple,
            layout=resolved, chunk_elems=chunk_elems, ring=ring,
            dense_stream=use_dense,
        )
        if cache_dir:
            key = ({**build_key, "dense_stream": True}
                   if auto_dense and use_dense else build_key)
            ds.save(cache_dir, build_key=key)
        return ds

    if path.startswith("tcp://"):
        from cfk_tpu.transport.ingest import collect_ratings
        from cfk_tpu.transport.tcp import TcpBrokerClient

        if fmt != "netflix" or min_rating:
            # Broker records are already-parsed (movieId, userId, rating)
            # wire frames; file-parse flags have nothing to apply to.
            _eprint(
                "warning: --format/--min-rating are ignored for tcp:// "
                "ingest (records on the broker are already parsed)"
            )
        host, port, topic = _parse_tcp_url(path)
        try:
            client = TcpBrokerClient(host, port)
        except OSError as e:
            # Broker down — a matching cache can still train offline, minus
            # the offset freshness check (which needs the broker).  The
            # non-offset key fields must still match exactly.
            ds = _cache_sans_fingerprint(cache_dir, build_key, Dataset,
                                         ignore=("end_offsets",),
                                         auto_dense=auto_dense)
            if ds is not None:
                _eprint(
                    f"warning: broker unreachable ({e}); using dataset cache "
                    "without the end-offset freshness check"
                )
                return ds
            raise
        with client:
            if cache_dir:
                from cfk_tpu.transport.tcp import BrokerRequestError

                try:
                    build_key["end_offsets"] = [
                        client.end_offset(topic, p)
                        for p in range(client.num_partitions(topic))
                    ]
                except BrokerRequestError as e:
                    # Topic gone (e.g. deleted after caching): a matching
                    # cache is the only way to train; offsets unverifiable.
                    ds = _cache_sans_fingerprint(
                        cache_dir, build_key, Dataset,
                        ignore=("end_offsets",), auto_dense=auto_dense)
                    if ds is not None:
                        _eprint(
                            f"warning: topic unavailable ({e}); using "
                            "dataset cache without the end-offset check"
                        )
                        return ds
                    raise
            return cache_or_build(lambda: collect_ratings(client, topic=topic))
    if os.path.exists(path):
        st = os.stat(path)
        build_key["data_size"] = st.st_size
        build_key["data_mtime_ns"] = st.st_mtime_ns
    else:
        # Source file gone (archived/deleted after caching) — a cache whose
        # key matches on everything but the file fingerprint still trains.
        ds = _cache_sans_fingerprint(cache_dir, build_key, Dataset,
                                     ignore=("data_size", "data_mtime_ns"),
                                     auto_dense=auto_dense)
        if ds is not None:
            _eprint(
                f"warning: data file {path!r} not found; using dataset "
                "cache without the size/mtime freshness check"
            )
            return ds
    if fmt == "netflix":
        return cache_or_build(lambda: parse_netflix(path))
    return cache_or_build(lambda: parse_movielens_csv(path, min_rating=min_rating))


def _cache_sans_fingerprint(cache_dir, build_key, Dataset, ignore,
                            auto_dense=False):
    """Load a cache whose content fingerprint cannot be recomputed (broker
    unreachable, source file deleted), if the stored build key matches ours
    on every field outside ``ignore``.

    ``auto_dense`` applies the same dual-key rule as the online path: a
    layout='auto' + dense_stream run matches a stored key WITH the
    ``dense_stream`` flag (its own prior dense-resolved-tiled save) or one
    without it — but a flagless cache that turns out to be tiled is a
    padded-stream build and must not serve a dense request."""
    import os
    import zipfile

    from cfk_tpu.data.cache import read_build_key

    if not cache_dir or not os.path.exists(os.path.join(cache_dir, "meta.json")):
        return None
    try:
        stored = read_build_key(cache_dir)
        if stored is None:
            return None
        strip = lambda k: {x: v for x, v in k.items() if x not in ignore}
        s, b = strip(stored), strip(build_key)
        flagged_ok = auto_dense and s == {**b, "dense_stream": True}
        if s != b and not flagged_ok:
            return None
        ds = Dataset.load(cache_dir, expect_build_key=stored)
        if auto_dense and not flagged_ok:
            from cfk_tpu.data.blocks import TiledBlocks

            if isinstance(ds.user_blocks, TiledBlocks):
                return None
        return ds
    except (ValueError, KeyError, OSError, zipfile.BadZipFile):
        return None


import contextlib as _contextlib


@_contextlib.contextmanager
def _telemetry_session(args, metrics=None):
    """Wire the telemetry subsystem for one CLI command (ISSUE 14).

    ``--trace-dir`` installs the host span tracer (Chrome-trace JSON
    written at exit, colocated with ``--profile-dir``'s jax-profiler trace
    when both point at the same directory); the flight recorder's dump
    directory resolves to the trace dir, else the checkpoint/stream dir,
    so any trip/escalation/eviction/crash leaves its forensic dump next to
    the run's other artifacts; ``--metrics-jsonl`` streams periodic
    registry snapshots for training dashboards."""
    from cfk_tpu import telemetry

    trace_dir = getattr(args, "trace_dir", None)
    dump_dir = (trace_dir
                or getattr(args, "checkpoint_dir", None)
                or getattr(args, "stream_dir", None))
    tracer = None
    if trace_dir:
        tracer = telemetry.configure(trace_dir=trace_dir)
    if dump_dir:
        telemetry.get_recorder().configure(dump_dir=dump_dir)
        telemetry.install_crash_hooks()
    emitter = None
    jsonl = getattr(args, "metrics_jsonl", None)
    if jsonl and metrics is not None:
        emitter = telemetry.MetricsEmitter(
            metrics, jsonl,
            interval_s=getattr(args, "metrics_interval_s", 10.0),
        ).start()
    try:
        yield
    finally:
        if emitter is not None:
            emitter.stop()
        if tracer is not None:
            path = telemetry.shutdown(write=True)
            if path:
                _eprint(f"host span trace written to {path}")


def _train(args) -> int:
    from cfk_tpu.utils.metrics import Metrics

    metrics = Metrics()
    with _telemetry_session(args, metrics):
        return _train_impl(args, metrics)


def _train_impl(args, metrics) -> int:
    from cfk_tpu.config import ALSConfig, set_async_collective_permute
    from cfk_tpu.eval.metrics import mse_rmse_from_model
    from cfk_tpu.eval.predict import save_prediction_csv
    from cfk_tpu.models.als import train_als
    from cfk_tpu.models.ials import IALSConfig, train_ials, train_ials_sharded
    from cfk_tpu.utils.metrics import maybe_profile

    # Must land in LIBTPU_INIT_ARGS before the first jax computation (the
    # dataset load below initializes the backend, which is when libtpu
    # reads the env on TPU; never XLA_FLAGS — CPU/GPU-only XLA aborts on
    # the unknown TPU flag).
    set_async_collective_permute(args.async_collective_permute)
    if args.layout == "auto" and args.exchange == "auto":
        # The per-half exchange builds on the tiled layout only (config
        # validation says so); resolve up front so ring blocks are built.
        args.layout = "tiled"
    if args.layout == "auto" and args.exchange == "ring":
        # Both ring-capable layouts work; padded needs no build-time ring
        # blocks and has no per-shard accumulator cap — the safe default
        # (pass --layout tiled explicitly for the tiled ring).
        args.layout = "padded"
    if args.layout == "auto" and args.exchange == "hier_ring":
        # The hierarchical exchange runs on the tiled ring blocks only.
        args.layout = "tiled"
    if args.layout == "auto" and args.offload_tier == "host_window":
        # The windowed host-offload driver streams the tiled stream-mode
        # layout; resolve up front so config validation never refuses a
        # flag combination the parser accepted.
        args.layout = "tiled"

    def _resolver(coo):
        return _resolve_auto_layout(coo, args.algorithm, args.solve_chunk)

    with metrics.phase("ingest"):
        ds = _load_dataset(
            args.data, args.format, args.min_rating, args.shards,
            args.pad_multiple, args.layout, args.chunk_elems,
            cache_dir=args.dataset_cache,
            ring=(
                (args.exchange if args.exchange == "auto"
                 else args.exchange in ("ring", "hier_ring"))
                if args.layout == "tiled" else False
            ),
            auto_resolver=_resolver,
            auto_key={
                "algorithm": args.algorithm,
                "solve_chunk": args.solve_chunk,
            },
            # The unpadded dense gather stream is the measured at-scale
            # default for BOTH models (round 5): explicit ALS 0.707 →
            # 0.652 s/iter full Netflix rank 64 (round 4), and — with the
            # sqrt-reparameterized weight stream replacing round 4's
            # premultiplied second stream — iALS ML-25M rank 128 0.662 →
            # 0.630 s/iter (the dense builder always stages the
            # rating_dense channel the weighted path needs).  Subspace
            # optimizers (als++/ials++) use padded/bucketed layouts, and
            # an explicit --exchange ring build carries the accum
            # machinery on both halves — the flag has no half to apply to
            # there, so don't request it (avoids the builder's warning).
            dense_stream=args.exchange not in ("ring", "hier_ring"),
        )
    if args.layout == "auto":
        # Reflect what _resolve_auto_layout (or a cache hit) actually built,
        # so the config matches the blocks.
        from cfk_tpu.data.blocks import (
            BucketedBlocks, SegmentBlocks, TiledBlocks,
        )

        args.layout = {
            BucketedBlocks: "bucketed",
            SegmentBlocks: "segment",
            TiledBlocks: "tiled",
        }.get(type(ds.movie_blocks), "padded")
    common = dict(
        layout=args.layout,
        rank=args.rank,
        lam=args.lam,
        num_iterations=args.iterations,
        seed=args.seed,
        num_shards=args.shards,
        exchange=args.exchange,
        ici_group=args.ici_group,
        offload_tier=args.offload_tier,
        staging=args.staging,
        staging_pool_depth=args.staging_pool_depth,
        hot_rows=args.hot_rows,
        compile_cache_dir=args.compile_cache_dir,
        overlap=not args.no_overlap,
        in_kernel_gather=(
            None if args.in_kernel_gather == "auto"
            else args.in_kernel_gather == "on"
        ),
        reg_solve_algo=args.reg_solve_algo,
        table_dtype=args.table_dtype,
        async_collective_permute=args.async_collective_permute,
        dtype=args.dtype,
        solver=args.solver,
        solve_chunk=args.solve_chunk,
        hbm_chunk_elems=args.chunk_elems,
        pad_multiple=args.pad_multiple,
        algorithm=args.algorithm,
        block_size=args.block_size,
        sweeps=args.sweeps,
        health_check_every=args.health_check_every,
        health_norm_limit=args.health_norm_limit,
        max_recoveries=args.max_recoveries,
        lam_escalation=args.lam_escalation,
        on_unrecoverable=args.on_unrecoverable,
    )
    heldout = train_coo = None
    if args.eval_ranking:
        if not args.implicit:
            _eprint("error: --eval-ranking requires --implicit (it is a "
                    "top-K ranking protocol, not a rating-error one)")
            return 1
        from cfk_tpu.data.blocks import Dataset
        from cfk_tpu.eval.ranking import leave_one_out_split

        d = ds.coo_dense
        train_coo, heldout = leave_one_out_split(
            d.movie_raw, d.user_raw, d.rating, seed=args.seed
        )
        before = (ds.movie_map.num_entities, ds.user_map.num_entities)
        ds = Dataset.from_coo(
            train_coo, num_shards=args.shards, pad_multiple=args.pad_multiple,
            layout=args.layout, chunk_elems=args.chunk_elems,
        )
        if (ds.movie_map.num_entities, ds.user_map.num_entities) != before:
            _eprint(
                "error: the leave-one-out split removed some entity's only "
                "interaction; ranking eval needs every movie to keep >= 1 — "
                "use a denser dataset"
            )
            return 1

    manager = _make_checkpoint_manager(args)
    if isinstance(manager, int):
        return manager
    ck = dict(checkpoint_manager=manager, checkpoint_every=args.checkpoint_every)

    # Preemption tolerance is on whenever a checkpoint store exists: an
    # eviction SIGTERM (or Ctrl-C) drains the async writer, commits one
    # final checkpoint, and the process exits resumable — re-run the same
    # command to continue (cfk_tpu.resilience.preempt).
    import contextlib

    guard_cm = contextlib.nullcontext(None)
    if manager is not None and not getattr(args, "no_preempt_save", False):
        from cfk_tpu.resilience.preempt import PreemptionGuard

        guard_cm = PreemptionGuard()

    with maybe_profile(args.profile_dir), guard_cm as guard:
        ck["preemption_guard"] = guard
        if args.implicit:
            config = IALSConfig(alpha=args.alpha, **common)
            if args.shards > 1:
                from cfk_tpu.parallel.mesh import make_mesh

                model = train_ials_sharded(
                    ds, config, make_mesh(args.shards), metrics=metrics, **ck
                )
            else:
                model = train_ials(ds, config, metrics=metrics, **ck)
        else:
            config = ALSConfig(**common)
            if args.shards > 1:
                from cfk_tpu.parallel.mesh import make_mesh
                from cfk_tpu.parallel.spmd import train_als_sharded

                model = train_als_sharded(
                    ds, config, make_mesh(args.shards), metrics=metrics, **ck
                )
            else:
                model = train_als(ds, config, metrics=metrics, **ck)

    if guard is not None and guard.triggered:
        # Exit inside the platform's SIGTERM grace window: the checkpoint
        # is committed and drained, so evaluation / ranking / the CSV dump
        # on the partial model would only risk a SIGKILL mid-eval.  The
        # metrics line still goes out — it carries the "preempted" note.
        _eprint(
            f"preempted ({guard.signal_name}): a final checkpoint was "
            "committed — re-run this command to resume; skipping "
            "evaluation and output for the partial run"
        )
        print(metrics.json_line() if args.metrics == "json"
              else metrics.logfmt())
        return 0

    # Both evals stream from the factors (never materializing U·Mᵀ), so they
    # run at scales where the dense matrix cannot exist; only the CSV dump
    # still needs dense predictions, and only it is skipped (with a warning)
    # when they're unmaterializable.
    if not args.implicit:
        with metrics.phase("eval_mse"):
            mse, rmse = mse_rmse_from_model(model, ds)
        metrics.gauge("mse", round(mse, 6))
        metrics.gauge("rmse", round(rmse, 6))
        _eprint(f"train MSE={mse:.4f} RMSE={rmse:.4f}")
    if heldout is not None:
        from cfk_tpu.eval.ranking import ranking_metrics_from_model

        with metrics.phase("eval_ranking"):
            rec, mpr = ranking_metrics_from_model(
                model, train_coo, heldout, k=args.eval_ranking
            )
        metrics.gauge(f"recall_at_{args.eval_ranking}", round(rec, 6))
        metrics.gauge("mpr", round(mpr, 6))
        _eprint(
            f"leave-one-out Recall@{args.eval_ranking}={rec:.4f} MPR={mpr:.4f}"
        )
    if args.output != "none":
        with metrics.phase("predict"):
            try:
                preds = model.predict_dense()
            except ValueError as e:
                # At full-Netflix scale the trained model is the deliverable;
                # don't discard it over an unmaterializable side product.
                preds = None
                _eprint(f"warning: skipping the prediction CSV dump: {e}")
        if preds is not None:
            with metrics.phase("dump_csv"):
                path = save_prediction_csv(
                    preds, None if args.output == "auto" else args.output
                )
            _eprint(f"predictions written to {path}")
    print(metrics.json_line() if args.metrics == "json" else metrics.logfmt())
    return 0


def _journal_transport(journal: str, *, fsync: bool):
    """Transport for a --checkpoint-journal target: tcp://HOST:PORT broker
    or a FileBroker directory.  Raises ValueError on a malformed URL and
    OSError when the broker is unreachable — callers turn both into clean
    CLI errors."""
    if journal.startswith("tcp://"):
        from cfk_tpu.transport.tcp import TcpBrokerClient

        host, port, _ = _parse_tcp_url(journal, topic_optional=True)
        return TcpBrokerClient(host, port)
    from cfk_tpu.transport.filelog import FileBroker

    return FileBroker(journal, fsync=fsync)


def _make_checkpoint_manager(args):
    """The checkpoint store the train flags select: the npz directory
    (``--checkpoint-dir``, the fast local default), the transport journal
    (``--checkpoint-journal``, factors as FeatureRecord frames through a
    FileBroker dir or a ``tcp:HOST:PORT`` broker — the reference's
    topics-as-durable-checkpoint design, ``setup.sh:18-21``), or None.
    Returns an int exit code on flag errors."""
    journal = getattr(args, "checkpoint_journal", None)
    if args.checkpoint_dir and journal:
        _eprint("error: --checkpoint-dir and --checkpoint-journal are "
                "mutually exclusive")
        return 2
    if args.checkpoint_dir:
        from cfk_tpu.transport.checkpoint import CheckpointManager

        return CheckpointManager(
            args.checkpoint_dir,
            keep_last_n=getattr(args, "keep_last_n", None),
        )
    if journal:
        from cfk_tpu.transport.journal import JournalCheckpointManager

        try:
            # fsync per append for the training journal: the commit marker
            # must never reach disk before the factor frames it commits.
            transport = _journal_transport(journal, fsync=True)
        except (ValueError, OSError) as e:
            _eprint(f"error: {e}")
            return 2
        return JournalCheckpointManager(
            transport, num_partitions=args.journal_partitions
        )
    return None


def _run_reference_form(args) -> int:
    """The reference's 7-positional-arg invocation."""
    from cfk_tpu.config import ALSConfig
    from cfk_tpu.data.blocks import Dataset
    from cfk_tpu.data.netflix import parse_netflix
    from cfk_tpu.eval.metrics import mse_rmse_from_model
    from cfk_tpu.eval.predict import save_prediction_csv
    from cfk_tpu.models.als import train_als

    _eprint(f"app started: {time.strftime('%Y-%m-%d %H:%M:%S')}")
    coo = parse_netflix(args.path)
    _eprint(f"producer finished: {time.strftime('%Y-%m-%d %H:%M:%S')}")
    # NUM_PARTITIONS maps to device shards when that many devices exist;
    # otherwise fall back to one shard with a warning (the reference's
    # partitions are Kafka-internal and have no single-device meaning).
    num_shards = args.num_partitions
    mesh = None
    if num_shards > 1:
        try:
            from cfk_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(num_shards)
        except ValueError as e:
            _eprint(f"warning: NUM_PARTITIONS={num_shards} ignored ({e})")
            num_shards = 1
    ds = Dataset.from_coo(coo, num_shards=num_shards)
    if ds.movie_map.num_entities != args.num_movies:
        _eprint(
            f"warning: NUM_MOVIES={args.num_movies} but data has "
            f"{ds.movie_map.num_entities} rated movies (using the data)"
        )
    if ds.user_map.num_entities != args.num_users:
        _eprint(
            f"warning: NUM_USERS={args.num_users} but data has "
            f"{ds.user_map.num_entities} rated users (using the data)"
        )
    config = ALSConfig(
        rank=args.num_features,
        lam=args.lam,
        num_iterations=args.num_iterations,
        num_shards=num_shards,
    )
    if mesh is not None:
        from cfk_tpu.parallel.spmd import train_als_sharded

        model = train_als_sharded(ds, config, mesh)
    else:
        model = train_als(ds, config)
    mse, rmse = mse_rmse_from_model(model, ds)
    try:
        preds = model.predict_dense()
    except ValueError as e:
        # Full-Netflix-scale run of the reference form: the dense CSV is the
        # one unmaterializable artifact; keep the quality numbers.
        preds = path = None
        _eprint(f"warning: skipping the prediction CSV dump: {e}")
    if preds is not None:
        path = save_prediction_csv(preds)
        _eprint(f"prediction matrix written: {time.strftime('%Y-%m-%d %H:%M:%S')}")
    print(f"MSE: {mse}")
    print(f"RMSE: {rmse}")
    if path is not None:
        print(path)
    return 0


def _evaluate(args) -> int:
    from cfk_tpu.data.blocks import Dataset
    from cfk_tpu.data.netflix import parse_netflix
    from cfk_tpu.eval.metrics import mse_rmse_from_blocks
    from cfk_tpu.eval.predict import load_prediction_csv

    coo = parse_netflix(args.ratings_file)
    ds = Dataset.from_coo(coo)
    preds = load_prediction_csv(args.prediction_csv)
    want = (ds.user_map.num_entities, ds.movie_map.num_entities)
    if preds.shape != want:
        _eprint(
            f"error: prediction matrix is {preds.shape}, ratings imply {want} "
            "(rows = users ascending id, cols = movies ascending id)"
        )
        return 2
    print(f"#users in ratings_matrix:  {want[0]}")
    print(f"#movies in ratings_matrix:  {want[1]}")
    mse, rmse = mse_rmse_from_blocks(preds, ds)
    print(f"MSE: {mse}")
    print(f"RMSE: {rmse}")
    return 0


def _serving_state(args):
    """Restore factors for the serving subcommands from either store:
    --checkpoint-dir (npz directory) or --checkpoint-journal (transport
    journal — a FileBroker directory or tcp://HOST:PORT broker)."""
    if bool(args.checkpoint_dir) == bool(args.checkpoint_journal):
        _eprint("error: pass exactly one of --checkpoint-dir / "
                "--checkpoint-journal")
        return None
    try:
        if args.checkpoint_dir:
            from cfk_tpu.transport.checkpoint import CheckpointManager

            return CheckpointManager(args.checkpoint_dir).restore()
        from cfk_tpu.transport.journal import JournalCheckpointManager

        transport = _journal_transport(args.checkpoint_journal, fsync=False)
        return JournalCheckpointManager(transport).restore()
    except (ValueError, OSError) as e:
        # Malformed URL, unreachable broker, or an empty/uncommitted store —
        # common operator mistakes; a clean error beats a traceback.
        _eprint(f"error: {e}")
        return None


def _predict(args) -> int:
    """Dump the prediction CSV from checkpointed factors, no retraining.

    The reference's final-collection phase (``processors/FeatureCollector.java``:
    P = U·Mᵀ + CSV dump) as a standalone step over the durable factor store —
    train once with --checkpoint-dir, then regenerate/evaluate predictions at
    any time.
    """
    from cfk_tpu.data.blocks import RatingsIndex
    from cfk_tpu.data.movielens import parse_movielens_csv
    from cfk_tpu.data.netflix import parse_netflix
    from cfk_tpu.eval.predict import save_prediction_csv
    from cfk_tpu.models.als import ALSModel

    if args.format == "netflix":
        coo = parse_netflix(args.data)
    else:
        coo = parse_movielens_csv(args.data, min_rating=args.min_rating)
    ds = RatingsIndex.from_coo(coo)
    state = _serving_state(args)
    if state is None:
        return 2
    if state.user_factors.shape[0] < ds.user_map.num_entities or (
        state.movie_factors.shape[0] < ds.movie_map.num_entities
    ):
        _eprint(
            f"error: checkpoint factors ({state.user_factors.shape[0]} users, "
            f"{state.movie_factors.shape[0]} movies) are smaller than the "
            f"data implies ({ds.user_map.num_entities}, "
            f"{ds.movie_map.num_entities}); wrong --data for this checkpoint?"
        )
        return 1
    model = ALSModel(
        user_factors=state.user_factors,
        movie_factors=state.movie_factors,
        num_users=ds.user_map.num_entities,
        num_movies=ds.movie_map.num_entities,
    )
    path = save_prediction_csv(
        model.predict_dense(), None if args.output == "auto" else args.output
    )
    _eprint(
        f"predictions from iteration-{state.iteration} checkpoint "
        f"written to {path}"
    )
    return 0


def _recommend(args) -> int:
    """Serve top-K from checkpointed factors, printing raw ids."""
    import numpy as np

    from cfk_tpu.data.blocks import RatingsIndex
    from cfk_tpu.data.movielens import parse_movielens_csv
    from cfk_tpu.data.netflix import parse_netflix
    from cfk_tpu.models.als import ALSModel

    # Only the id maps + seen lists are needed — never build solve blocks
    # (a padded rectangle at full-Netflix scale would dwarf serving memory).
    if args.format == "netflix":
        coo = parse_netflix(args.data)
    else:
        coo = parse_movielens_csv(args.data, min_rating=args.min_rating)
    ds = RatingsIndex.from_coo(coo)
    state = _serving_state(args)
    if state is None:
        return 2
    model = ALSModel(
        user_factors=state.user_factors,
        movie_factors=state.movie_factors,
        num_users=ds.user_map.num_entities,
        num_movies=ds.movie_map.num_entities,
    )
    if args.users == "all":
        rows = np.arange(ds.user_map.num_entities)
    else:
        raw = np.asarray([int(u) for u in args.users.split(",")], dtype=np.int64)
        rows = ds.user_map.to_dense(raw).astype(np.int64)
    scores, movie_rows = model.recommend_top_k(
        rows, args.k, dataset=None if args.include_seen else ds
    )
    raw_movies = ds.movie_map.raw_ids[movie_rows]
    raw_users = ds.user_map.raw_ids[rows]
    for i, u in enumerate(raw_users):
        pairs = ",".join(
            f"{mid}:{s:.3f}" for mid, s in zip(raw_movies[i], scores[i])
        )
        print(f"{u}\t{pairs}")
    return 0


def _serve(args) -> int:
    """Top-K request server over the transport log (ISSUE 8).

    Restores factors from the checkpoint store, builds the serving engine
    (quantized table per --table-dtype, exclude-seen from --data's rating
    lists), and serves score requests:

    - with --broker tcp://HOST:PORT, joins the native broker's
      serve-requests/serve-responses topics and answers until killed —
      the cross-process deployment form;
    - without --broker, runs the built-in open-loop load generator
      against an in-memory log (--loadgen-qps/--loadgen-requests) and
      prints the measured QPS/p50/p99 row — the self-contained smoke
      (the recorded-at-scale numbers live in ``bench.py --serve``).

    ``--metrics-port`` makes the server answer ``GET /metrics``
    (Prometheus text) while it serves; ``--trace-dir`` writes the host
    span trace (batch assemble/compute/respond timeline) at exit.

    ``--replicas N`` (ISSUE 18) serves through the replicated fleet
    instead: N replicas behind the request log (one partition each,
    user-keyed routing), each with its own /metrics + /readyz and
    optional admission control (``--admission-queue``).
    """
    with _telemetry_session(args):
        return _serve_impl(args)


def _serve_impl(args) -> int:
    import numpy as np

    from cfk_tpu.data.blocks import RatingsIndex
    from cfk_tpu.data.movielens import parse_movielens_csv
    from cfk_tpu.data.netflix import parse_netflix
    from cfk_tpu.models.als import ALSModel
    from cfk_tpu.serving import (
        RecommendServer,
        ServeClient,
        engine_from_model,
        ensure_serve_topics,
        run_open_loop,
        warm_serve_programs,
        zipf_user_rows,
    )

    # Before the first compile (ISSUE 13): warm-start compile caching —
    # a restarted server replays its serve programs from the persistent
    # cache instead of recompiling the whole bucket set.
    from cfk_tpu.config import enable_compile_cache

    enable_compile_cache(args.compile_cache_dir)
    if args.format == "netflix":
        coo = parse_netflix(args.data)
    else:
        coo = parse_movielens_csv(args.data, min_rating=args.min_rating)
    ds = RatingsIndex.from_coo(coo)
    state = _serving_state(args)
    if state is None:
        return 2
    model = ALSModel(
        user_factors=state.user_factors,
        movie_factors=state.movie_factors,
        num_users=ds.user_map.num_entities,
        num_movies=ds.movie_map.num_entities,
    )
    engine = engine_from_model(
        model, None if args.include_seen else ds,
        table_dtype=args.table_dtype, tile_m=args.tile_m,
        serve_mode=args.serve_mode, clusters=args.clusters or None,
        probe_clusters=args.probe_clusters or None,
    )
    if engine.serve_mode == "two_stage":
        _eprint(
            f"two-stage retrieval: {engine.clusters} clusters, "
            f"{engine.probe_clusters} probed per user "
            "(exact scan remains the fault fallback)"
        )
    # Trace/compile the pow2 batch-bucket set before traffic arrives
    # (ISSUE 13): the first real batch then pays zero traces.
    warm = engine.prewarm(args.k, max_batch=args.max_batch)
    _eprint(
        f"prewarmed {warm['programs']} serve programs "
        f"({warm['new_traces']} new traces) in {warm['prewarm_s']:.2f}s"
    )
    # Replicated fleet (ISSUE 18): N replicas behind the request log —
    # user-keyed routing, per-replica /metrics + /readyz, admission
    # control, delta/rollover plumbing ready for a publisher to join.
    def _fleet(transport):
        from cfk_tpu.serving import ServeFleet

        fleet = ServeFleet(
            lambda i: engine if i == 0 else engine_from_model(
                model, None if args.include_seen else ds,
                table_dtype=args.table_dtype, tile_m=args.tile_m,
                serve_mode=args.serve_mode, clusters=args.clusters or None,
                probe_clusters=args.probe_clusters or None,
            ),
            transport, replicas=args.replicas, max_batch=args.max_batch,
            admission_max_queue=args.admission_queue or None,
            metrics_ports=args.metrics_port is not None,
        )
        fleet.seed_store(model.user_factors, model.movie_factors,
                         num_users=model.num_users)
        fleet.prewarm(args.k, max_batch=args.max_batch)
        for r in fleet.replicas:
            ms = r.server.metrics_server
            if ms is not None:
                _eprint(f"replica {r.index} metrics endpoint: {ms.url}")
        return fleet

    if args.broker:
        host, port, _ = _parse_tcp_url(args.broker, topic_optional=True)
        from cfk_tpu.transport.tcp import TcpBrokerClient

        transport = TcpBrokerClient(host, port)
        if args.replicas > 1:
            import time as _time

            fleet = _fleet(transport).start()
            _eprint(
                f"serving fleet: {args.replicas} replicas over broker "
                f"{host}:{port} (user-keyed routing; ^C to stop)"
            )
            try:
                while True:
                    _time.sleep(1.0)
            except KeyboardInterrupt:  # pragma: no cover - interactive
                pass
            finally:
                fleet.stop()
            c = fleet.counters()
            _eprint(f"fleet served {c['served']} requests "
                    f"({c['shed']} shed) in {c['batches']} batches")
            return 0
        ensure_serve_topics(
            transport, request_partitions=args.request_partitions,
            response_partitions=args.response_partitions,
        )
        server = RecommendServer(engine, transport,
                                 max_batch=args.max_batch,
                                 metrics_port=args.metrics_port)
        if server.metrics_server is not None:
            _eprint(f"metrics endpoint: {server.metrics_server.url}")
        _eprint(
            f"serving {ds.user_map.num_entities} users × "
            f"{ds.movie_map.num_entities} movies (rank "
            f"{state.user_factors.shape[-1]}, table {engine.table_dtype}) "
            f"from broker {host}:{port}; ^C to stop"
        )
        try:
            server.serve_forever()
        except KeyboardInterrupt:  # pragma: no cover - interactive
            pass
        finally:
            server.close()
        _eprint(f"served {server.requests_served} requests "
                f"in {server.batches} batches")
        return 0
    from cfk_tpu.transport import InMemoryBroker

    transport = InMemoryBroker()
    if args.replicas > 1:
        import json

        fleet = _fleet(transport).start()
        client = ServeClient(transport, route_by_user=True)
        pool = zipf_user_rows(
            ds.user_map.num_entities, args.loadgen_requests, seed=args.seed
        )
        try:
            report = run_open_loop(
                client, rate_qps=args.loadgen_qps,
                num_requests=args.loadgen_requests, user_rows=pool,
                k=args.k,
            )
        finally:
            fleet.stop()
        c = fleet.counters()
        print(json.dumps({
            "users": ds.user_map.num_entities,
            "movies": ds.movie_map.num_entities,
            "k": args.k,
            "table_dtype": engine.table_dtype,
            "replicas": args.replicas,
            "shed": c["shed"],
            "client_retries": client.retries,
            **report.as_row(),
            # the loadgen can't see the fleet's servers — batch
            # accounting comes from the fleet counters instead
            "batches": c["batches"],
            "mean_batch": (round(c["served"] / c["batches"], 1)
                           if c["batches"] else 0.0),
        }))
        return 0
    ensure_serve_topics(transport)
    server = RecommendServer(engine, transport, max_batch=args.max_batch,
                             metrics_port=args.metrics_port)
    if server.metrics_server is not None:
        _eprint(f"metrics endpoint: {server.metrics_server.url}")
    client = ServeClient(transport)
    pool = zipf_user_rows(
        ds.user_map.num_entities, args.loadgen_requests, seed=args.seed
    )
    try:
        warm_serve_programs(client, server, pool, args.k,
                            min(args.max_batch, pool.shape[0]))
        report = run_open_loop(
            client, rate_qps=args.loadgen_qps,
            num_requests=args.loadgen_requests, user_rows=pool, k=args.k,
            server=server, drive_server=True,
        )
    finally:
        server.close()
    import json

    print(json.dumps({
        "users": ds.user_map.num_entities,
        "movies": ds.movie_map.num_entities,
        "k": args.k,
        "table_dtype": engine.table_dtype,
        **report.as_row(),
    }))
    return 0


def _broker(args) -> int:
    """Run the native broker server in the foreground."""
    import subprocess

    from cfk_tpu.transport.tcp import _BROKER_BIN, build_broker

    if not build_broker(quiet=False):
        _eprint("error: cfk_broker binary unavailable (make -C native failed)")
        return 1
    argv = [_BROKER_BIN, str(args.port)]
    if args.data_dir or args.bind != "127.0.0.1":
        argv.append(args.data_dir or "")
    if args.bind != "127.0.0.1":
        argv.append(args.bind)
    try:
        return subprocess.run(argv).returncode
    except KeyboardInterrupt:
        return 0


def _topics(args) -> int:
    """Topic administration against a running broker — the role of the
    reference's ``setup.sh`` (delete + recreate topics out-of-band,
    ``setup.sh:14-24``), without a second copy of the partition count."""
    from cfk_tpu.transport.tcp import TcpBrokerClient

    host, port, topic = _parse_tcp_url(args.broker, topic_optional=True)
    with TcpBrokerClient(host, port) as client:
        if args.action == "list":
            for name in client.topics():
                nparts = client.num_partitions(name)
                print(
                    f"{name}\tpartitions={nparts}\t"
                    + "\t".join(
                        f"p{p}={client.end_offset(name, p)}"
                        for p in range(nparts)
                    )
                )
            return 0
        if topic is None:
            _eprint(f"error: {args.action} needs tcp://HOST:PORT/TOPIC")
            return 1
        if args.action == "create":
            client.create_topic(topic, args.partitions)
        elif args.action == "delete":
            client.delete_topic(topic)
        elif args.action == "recreate":
            client.delete_topic(topic)
            client.create_topic(topic, args.partitions)
    return 0


def _produce(args) -> int:
    """Stream a Netflix-format ratings file into a broker topic.

    The reference's producer-then-app sequencing (``apps/ALSAppRunner.java:30-33``)
    as two processes: ``cfk_tpu produce`` here, ``cfk_tpu train --data
    tcp://...`` there.
    """
    from cfk_tpu.transport.ingest import produce_ratings_file
    from cfk_tpu.transport.tcp import TcpBrokerClient

    host, port, topic = _parse_tcp_url(args.broker)
    if args.partitions < 1:
        _eprint(f"error: --partitions must be >= 1, got {args.partitions}")
        return 1
    with TcpBrokerClient(host, port) as client:
        try:
            client.create_topic(topic, args.partitions)
        except ValueError as e:
            if "already exists" not in str(e):
                raise
            if not args.append:
                _eprint(
                    f"error: topic {topic!r} already exists (use --append to "
                    "add to a topic produced with --no-eof; a finalized "
                    "topic's EOF records would fail the ingest barrier)"
                )
                return 1
        n = produce_ratings_file(
            client, args.data, topic=topic, send_eof=not args.no_eof
        )
    state = "open (no EOF yet)" if args.no_eof else "finalized"
    _eprint(f"produced {n} ratings to {topic!r} on {host}:{port} [{state}]")
    return 0


def _updates_transport(updates: str, *, fsync: bool = True):
    """Transport for --updates: tcp://HOST:PORT broker or a FileBroker
    directory (the durable default — the updates topic is the system of
    record the crash replay consumes)."""
    if updates.startswith("tcp://"):
        from cfk_tpu.transport.tcp import TcpBrokerClient

        host, port, _ = _parse_tcp_url(updates, topic_optional=True)
        return TcpBrokerClient(host, port)
    from cfk_tpu.transport.filelog import FileBroker

    return FileBroker(updates, fsync=fsync)


def _stream(args) -> int:
    """Streaming fold-in: consume rating updates, fold them into live
    factors, commit factors + offset cursor atomically per micro-batch.

    Bootstrap: with no resumable state in --stream-dir, a base model is
    trained from --data first (same config), then streaming starts from
    offset 0.  Re-running the identical command resumes from the committed
    cursor — including after a crash or an eviction SIGTERM.
    ``--produce-csv`` instead appends "user,movie,rating" lines to the
    updates topic and exits (the producer side of the loop).
    ``--metrics-port`` serves the live registry as Prometheus text on
    ``GET /metrics`` for the duration of the stream."""
    from cfk_tpu.utils.metrics import Metrics

    metrics = Metrics()
    with _telemetry_session(args, metrics):
        http = None
        if getattr(args, "metrics_port", None) is not None:
            from cfk_tpu.telemetry import MetricsHTTPServer

            http = MetricsHTTPServer(
                metrics, port=args.metrics_port
            ).start()
            _eprint(f"metrics endpoint: {http.url}")
        try:
            return _stream_impl(args, metrics)
        finally:
            if http is not None:
                http.stop()


def _stream_impl(args, metrics) -> int:
    from cfk_tpu.config import ALSConfig

    try:
        transport = _updates_transport(args.updates)
    except (ValueError, OSError) as e:
        _eprint(f"error: {e}")
        return 2
    if args.produce_csv:
        from cfk_tpu.streaming import StreamProducer

        prod = StreamProducer(
            transport, num_partitions=args.partitions
        )
        # Parse the whole file first, then one bulk append per partition
        # (send_many → FileBroker.produce_frames): per-line send() pays
        # one fsync'd append each — minutes for a 100k-line file — and
        # parse-before-produce also makes a malformed line all-or-nothing
        # instead of leaving a half-produced file in the log.
        users: list[int] = []
        movies: list[int] = []
        ratings: list[float] = []
        with open(args.produce_csv) as f:
            for lineno, line in enumerate(f, start=1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    user_s, movie_s, rating_s = line.split(",", 2)
                    users.append(int(user_s))
                    movies.append(int(movie_s))
                    ratings.append(float(rating_s))
                except ValueError as e:
                    _eprint(
                        f"error: {args.produce_csv}:{lineno}: malformed "
                        f"update {line!r} ({e})"
                    )
                    return 1
        prod.send_many(users, movies, ratings)
        n = len(users)
        if hasattr(transport, "flush"):
            transport.flush()
        _eprint(f"produced {n} updates (next seq {prod.next_seq})")
        return 0

    from cfk_tpu.streaming import StreamConfig, StreamSession
    from cfk_tpu.transport.checkpoint import CheckpointManager

    config = ALSConfig(
        rank=args.rank,
        lam=args.lam,
        num_iterations=args.iterations,
        seed=args.seed,
        layout=args.layout,
        solver=args.solver,
        dtype=args.dtype,
        # threaded so retrain()'s merged-dataset rebuild honors the same
        # HBM chunk budget as the base dataset built below
        hbm_chunk_elems=args.chunk_elems,
        health_check_every=args.health_check_every,
        health_norm_limit=args.health_norm_limit,
        max_recoveries=args.max_recoveries,
        lam_escalation=args.lam_escalation,
        on_unrecoverable=args.on_unrecoverable,
        compile_cache_dir=args.compile_cache_dir,
    )
    # Ensure the topic BEFORE the (possibly hours-long) base train: a
    # fresh topic is created empty and followed, instead of training a
    # base model only to crash on an unknown-topic lookup afterwards.
    from cfk_tpu.streaming import ensure_updates_topic

    ensure_updates_topic(transport, num_partitions=args.partitions)
    with metrics.phase("ingest"):
        ds = _load_dataset(
            args.data, args.format, args.min_rating, 1, 8,
            args.layout, args.chunk_elems,
            cache_dir=args.dataset_cache,
            dense_stream=args.layout == "tiled",
        )
    manager = CheckpointManager(
        args.stream_dir, keep_last_n=args.keep_last_n
    )
    base_model = None
    if manager.latest_valid_iteration() is None:
        _eprint("no stream state yet: training the base model first")
        from cfk_tpu.models.als import train_als

        with metrics.phase("base_train"):
            base_model = train_als(ds, config, metrics=metrics)
    stream = StreamConfig(
        batch_records=args.batch_records,
        foldin_layout=args.foldin_layout,
        retrain_every=args.retrain_every,
    )
    import contextlib

    guard_cm = contextlib.nullcontext(None)
    if not args.no_preempt_save:
        from cfk_tpu.resilience.preempt import PreemptionGuard

        guard_cm = PreemptionGuard()
    with guard_cm as guard:
        session = StreamSession(
            ds, config, transport, manager, stream=stream,
            base_model=base_model, metrics=metrics,
            preemption_guard=guard,
        )
        if args.prewarm:
            warm = session.prewarm()
            _eprint(
                f"prewarmed {warm['programs']} fold-in programs "
                f"({warm['new_traces']} new traces) in "
                f"{warm['prewarm_s']:.2f}s"
            )
        model = session.run(
            max_batches=args.max_batches, follow=args.follow
        )
    metrics.gauge("stream_step", session.stream_step)
    metrics.gauge("users", session.state.num_users)
    metrics.gauge("backlog", session.backlog())
    if guard is not None and guard.triggered:
        _eprint(
            f"preempted ({guard.signal_name}): factor+cursor step "
            f"{session.stream_step} is committed — re-run to resume"
        )
    elif not args.no_eval:
        import dataclasses

        from cfk_tpu.eval.metrics import mse_rmse_from_model

        with metrics.phase("eval_mse"):
            # against the merged (base + committed upserts) rating state;
            # the merged dataset re-sorts ALL users ascending by raw id
            # while session rows are base-ascending THEN appended new
            # users, so the factors must be permuted into the merged row
            # order (same perm the warm retrain applies) or every user
            # past a new user's insertion point scores against the wrong
            # row
            from cfk_tpu.data.blocks import Dataset as _DS

            merged = _DS.from_coo(session.state.to_coo())
            perm = merged.user_map.to_dense(session.state.user_raw_ids())
            u_sess = np.asarray(model.user_factors)
            u_eval = np.zeros(
                (merged.user_blocks.padded_entities, u_sess.shape[1]),
                u_sess.dtype,
            )
            u_eval[perm] = u_sess[: session.state.num_users]
            eval_model = dataclasses.replace(
                model, user_factors=u_eval,
                num_users=merged.user_map.num_entities,
            )
            mse, rmse = mse_rmse_from_model(eval_model, merged)
        metrics.gauge("mse", round(mse, 6))
        metrics.gauge("rmse", round(rmse, 6))
        _eprint(f"merged-state MSE={mse:.4f} RMSE={rmse:.4f}")
    print(metrics.json_line() if args.metrics == "json"
          else metrics.logfmt())
    return 0


def _plan_cmd(args) -> int:
    """``cfk_tpu plan``: resolve + print an ExecutionPlan (ISSUE 9).

    The shape/device come from flags (no dataset needed — this is the
    offline side of the planner), 'auto' flags stay free for the
    resolver, anything concrete pins.  ``--explain`` prints the winner's
    cost terms and, per free knob, the estimated cost of flipping it —
    the "why this and not that" record.  ``--autotune`` measures the
    model's top candidates on a trimmed synthetic workload and caches
    the winner keyed by (shape-class, device fingerprint, version).
    """
    import json as _json

    from cfk_tpu.plan import (
        DeviceSpec,
        PlanConstraints,
        ProblemShape,
        plan as resolve_plan,
        plan_cost,
        rank_plans,
    )

    shape = ProblemShape(
        num_users=args.users, num_movies=args.movies,
        nnz=args.ratings, rank=args.rank, num_shards=args.shards,
        implicit=args.implicit, dtype=args.storage_dtype,
        kind="serve" if args.serve else "train", serve_k=args.serve_k,
    )
    tri = {"auto": None, "on": True, "off": False}
    cons = PlanConstraints(
        layout=None if args.layout == "auto" else args.layout,
        exchange=None if args.exchange == "auto" else args.exchange,
        table_dtype=(None if args.table_dtype == "auto"
                     else args.table_dtype),
        fused_epilogue=tri[args.fused],
        in_kernel_gather={"auto": None, "fused": True,
                          "xla": False}[args.gather],
        overlap=tri[args.overlap],
        reg_solve_algo=(None if args.reg_solve_algo == "auto"
                        else args.reg_solve_algo),
        solver=None if args.solver == "auto" else args.solver,
        chunk_elems=args.chunk_elems,
        offload_tier=(None if args.offload_tier == "auto"
                      else args.offload_tier),
        ici_group=args.ici_group,
        staging=None if args.staging == "auto" else args.staging,
        hot_rows=args.hot_rows,
        serve_mode=(None if args.serve_mode == "auto"
                    else args.serve_mode),
        clusters=args.clusters,
        probe_clusters=args.probe_clusters,
    )
    if args.device == "auto":
        device = DeviceSpec.detect()
    elif args.device == "v5e":
        device = DeviceSpec.nominal("tpu", name="v5e")
    else:
        device = DeviceSpec.nominal("cpu")
    mode = "autotune" if args.autotune else args.mode
    measure = None
    if args.autotune:
        if args.serve:
            raise ValueError(
                "--autotune measures the training iteration; warm the "
                "serve cache with perf_lab --serve --plan autotune"
            )
        from cfk_tpu.plan.autotune import measure_with_training

        measure = measure_with_training(shape)
    ep, prov = resolve_plan(shape, device, cons, mode=mode,
                            cache_path=args.cache_path, measure=measure)
    print(f"# shape  {shape.shape_class()}")
    print(f"# device {device.fingerprint()}")
    print(f"# plan   {prov.summary()}")
    if args.explain:
        cost = plan_cost(shape, device, ep)
        print("# cost terms:")
        for line in cost.explain_lines():
            print(f"#   {line}")
        for field, value, reason in prov.explain:
            if reason == "cost term (s)":
                continue  # already printed via explain_lines above
            print(f"#   {field}: {value} — {reason}")
        # Per-knob deltas: what would flipping each FREE knob cost?
        try:
            ranked = rank_plans(shape, device, cons)
        except Exception:  # pragma: no cover - pinned-everything case
            ranked = []
        best_knobs = ep.knob_dict()
        seen: set[str] = set()
        for s, alt in ranked[1:]:
            diff = {f: v for f, v in alt.knob_dict().items()
                    if v != best_knobs.get(f)}
            if len(diff) != 1:
                continue
            (f, v), = diff.items()
            tag = f"{f}={v}"
            if tag in seen:
                continue
            seen.add(tag)
            print(f"#   flip {tag}: {s:.6f} s "
                  f"(+{s - (prov.est_cost_s or s):.6f})")
    print(_json.dumps(
        {**ep.as_dict(), **prov.as_row()}, sort_keys=True
    ))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cfk_tpu", description=__doc__)
    p.add_argument(
        "--platform",
        choices=["default", "cpu", "tpu"],
        default="default",
        help="force the JAX platform (overrides environment registration)",
    )
    sub = p.add_subparsers(dest="command", required=True)

    r = sub.add_parser("run", help="reference-compatible positional form")
    r.add_argument("num_partitions", type=int)
    r.add_argument("num_features", type=int)
    r.add_argument("lam", type=float)
    r.add_argument("num_iterations", type=int)
    r.add_argument("path")
    r.add_argument("num_movies", type=int)
    r.add_argument("num_users", type=int)
    r.set_defaults(fn=_run_reference_form)

    t = sub.add_parser("train", help="full-flag training")
    t.add_argument("--data", required=True)
    t.add_argument("--format", choices=["netflix", "movielens"], default="netflix")
    t.add_argument("--implicit", action="store_true", help="confidence-weighted iALS")
    t.add_argument("--min-rating", type=float, default=0.0)
    t.add_argument("--rank", type=int, default=5)
    t.add_argument("--lam", type=float, default=0.05)
    t.add_argument("--alpha", type=float, default=40.0, help="iALS confidence weight")
    t.add_argument(
        "--algorithm", choices=["als", "als++", "ials++"], default="als",
        help="per-entity optimizer: 'als' = full k-by-k normal-equation "
        "solves (the reference's exact semantics); 'als++' (explicit) / "
        "'ials++' (implicit, Rendle et al.) = warm-started subspace block "
        "coordinate descent — much cheaper per epoch at large rank; "
        "padded/bucketed layouts",
    )
    t.add_argument(
        "--eval-ranking", type=int, default=None, metavar="K",
        help="(implicit only) hold one interaction per user out before "
        "training and report leave-one-out Recall@K and mean percentile "
        "rank after",
    )
    t.add_argument("--block-size", type=int, default=32,
                   help="als++/ials++ coordinate block size (must divide rank)")
    t.add_argument("--sweeps", type=int, default=1,
                   help="als++/ials++ sweeps over all blocks per half-iteration")
    t.add_argument("--iterations", type=int, default=7)
    t.add_argument("--seed", type=int, default=42)
    t.add_argument("--shards", type=int, default=1)
    t.add_argument("--exchange",
                   choices=["all_gather", "ring", "hier_ring", "auto"],
                   default="all_gather",
                   help="fixed-factor exchange; 'auto' (tiled layout) picks "
                   "per half: ring where the Gram accumulator fits, "
                   "all_gather elsewhere")
    t.add_argument(
        "--no-overlap", action="store_true",
        help="pin the serial exchange/compute schedule instead of the "
        "default double-buffered pipelines (A/B measurement; factors are "
        "bit-identical either way — see ARCHITECTURE.md 'Exchange/compute "
        "overlap')",
    )
    t.add_argument(
        "--in-kernel-gather", choices=["auto", "on", "off"], default="auto",
        help="fuse the per-chunk neighbor-factor gather into the pallas "
        "Gram kernels (rows DMA'd straight from the HBM-resident factor "
        "table into the kernel's VMEM double buffer — the materialized "
        "[C, k] gathered stream disappears).  'auto' (default) gathers "
        "in-kernel wherever the kernels' SMEM/alignment gates allow, "
        "falling back to the XLA-gather schedule otherwise; 'off' pins "
        "the XLA gather (A/B measurement; factors are bit-identical "
        "either way — see ARCHITECTURE.md 'In-kernel neighbor gather')",
    )
    t.add_argument(
        "--table-dtype", choices=["float32", "bfloat16", "int8"],
        default="float32",
        help="HBM gather-table dtype (cfk_tpu.ops.quant): quantize the "
        "fixed-side table each half-iteration gathers from — bfloat16 "
        "halves the gather bytes, int8 (+ one f32 scale per row, folded "
        "into the kernels' premultiply) quarters them; Gram/solve "
        "accumulation stays float32 and the solved factors keep --dtype. "
        "float32 (default) is bit-identical to pre-quantization behavior. "
        "int8 needs the tiled/bucketed layouts' weight streams",
    )
    t.add_argument(
        "--reg-solve-algo", choices=["auto", "lu", "gj"], default="auto",
        help="elimination algorithm of the fused reg+solve kernels: "
        "reverse no-pivot LU (rank cap 128) or Gauss-Jordan (cap 64); "
        "'auto' keeps the process default (lu).  Threaded as a real "
        "config parameter — the recovery ladder's GJ rung overrides it "
        "per-step",
    )
    t.add_argument(
        "--async-collective-permute", choices=["auto", "on", "off"],
        default="auto",
        help="force XLA's async collective-permute pass via "
        "LIBTPU_INIT_ARGS "
        "(the escape hatch for the ring overlap's transfer hiding); "
        "'auto' keeps the compiler default",
    )
    t.add_argument(
        "--solver", choices=["auto", "cholesky", "pallas"], default="auto",
        help="batched k-by-k solve backend: auto = pallas Gauss-Jordan "
        "kernel on TPU (rank <= 64), XLA cholesky elsewhere",
    )
    t.add_argument("--dtype", choices=["float32", "bfloat16"], default="float32")
    t.add_argument("--solve-chunk", type=int, default=None,
                   help="DEPRECATED: explicit entities per padded-layout "
                   "solve chunk; --chunk-elems is the one HBM budget for "
                   "every layout")
    t.add_argument("--pad-multiple", type=int, default=8)
    t.add_argument(
        "--layout",
        choices=["auto", "padded", "bucketed", "segment", "tiled"],
        default="auto",
        help="InBlock layout: one rectangle (padded), power-of-two width "
        "buckets (bucketed), flat segment runs with grouped ragged-matmul "
        "Grams (segment; exactly O(nnz) memory for arbitrarily skewed "
        "data), or tile-padded runs with batched-GEMM Grams via the fused "
        "pallas kernel (tiled; the fastest at full-Netflix scale). "
        "Default 'auto': padded below 2M ratings, tiled above",
    )
    t.add_argument(
        "--chunk-elems", type=int, default=1 << 20,
        help="the ONE HBM budget, in gather cells, for every layout: "
        "bucketed/segment/tiled consume it at dataset build time "
        "(ratings per scan chunk); padded derives entities per solve "
        "chunk from it at run time",
    )
    t.add_argument(
        "--offload-tier", choices=["auto", "device", "host_window"],
        default="auto",
        help="where the factor tables live (ISSUE 11/12): 'auto' lets "
        "the planner's PER-SHARD memory-budget predicate decide "
        "(resident while they fit — today's behavior); 'device' pins "
        "resident tables (refused up front when they cannot fit); "
        "'host_window' pins the out-of-core path — host-RAM factor "
        "stores with device_put-pipelined windows, sharded too (per-"
        "shard windows under the all_gather scan or ring/hier_ring "
        "visit schedules, int8 (codes, scales) PCIe staging; explicit "
        "ALS, tiled layout, bit-exact vs the resident paths)",
    )
    t.add_argument(
        "--ici-group", type=int, default=None, metavar="I",
        help="inner-ring size of --exchange hier_ring (devices per ICI "
        "domain); default: local device count when it divides --shards, "
        "else one flat ring",
    )
    t.add_argument(
        "--staging", choices=["auto", "pool", "serial"], default="auto",
        help="host staging engine of the host_window tier (ISSUE 13): "
        "'pool' (= 'auto', the default) overlaps every shard's window "
        "staging — store gather, host quantize, checksum, device_put — "
        "on a bounded thread pool across shards and windows; 'serial' "
        "pins the one-thread double buffer (the bench.py --staging-ab "
        "baseline).  Factors are crc-identical across the knob",
    )
    t.add_argument(
        "--hot-rows", type=int, default=None, metavar="F",
        help="skew-aware hot-row device cache of the host_window tier "
        "(ISSUE 15): keep the top-F most-referenced fixed-table rows "
        "(total, both sides) device-resident so windows stage only "
        "their cold delta.  Default: AUTO — the coverage-curve knee of "
        "the window plans' own reference counts, clamped by the budget "
        "headroom (resolves off when either refuses); 0 pins the cache "
        "off (the full-staging engine); an impossible F raises naming "
        "the bytes.  Factors are crc-identical across the knob",
    )
    t.add_argument(
        "--staging-pool-depth", type=int, default=None, metavar="D",
        help="windows staged ahead of consumption in pool mode "
        "(default: offload.staging.DEFAULT_POOL_DEPTH); always clamped "
        "so D+1 worst-case windows fit the per-shard window budget",
    )
    t.add_argument(
        "--compile-cache-dir", default=None, metavar="DIR",
        help="persistent jax compilation cache (ISSUE 13): compiled "
        "programs are reused across process restarts, keyed per device "
        "fingerprint inside DIR — a warm cache removes the cold-start "
        "compile cost the time_to_first_step/batch columns measure",
    )
    t.add_argument(
        "--health-check-every", type=int, default=None, metavar="N",
        help="arm the numerical-health sentinel: probe the factor state "
        "(isfinite + norm watchdogs, <2%% overhead at N=1) every N "
        "iterations; a tripped probe rolls back to the last good "
        "checkpoint and escalates (retry, then lam x LAM_ESCALATION, "
        "then split epilogue, then GJ elimination).  Default: off",
    )
    t.add_argument(
        "--health-norm-limit", type=float, default=1e6,
        help="factor-row 2-norm above which the sentinel's watchdog trips "
        "even while values are still finite (catches slow divergence "
        "before overflow)",
    )
    t.add_argument(
        "--max-recoveries", type=int, default=4,
        help="total sentinel trips tolerated before the run stops "
        "retrying (see --on-unrecoverable)",
    )
    t.add_argument(
        "--lam-escalation", type=float, default=10.0,
        help="multiplier applied to lam on the recovery ladder's "
        "regularization rung",
    )
    t.add_argument(
        "--on-unrecoverable", choices=["degrade", "raise"],
        default="degrade",
        help="after max-recoveries trips: 'degrade' returns the last-good "
        "factors with a diagnostic report in the metrics (a stale model "
        "beats no model); 'raise' fails the run",
    )
    t.add_argument("--checkpoint-dir", default=None)
    t.add_argument("--checkpoint-every", type=int, default=1)
    t.add_argument(
        "--keep-last-n", type=int, default=None,
        help="garbage-collect checkpoint steps beyond the newest N after "
        "each save (the last verified-good step the recovery ladder "
        "points at is always pinned); default keeps every step",
    )
    t.add_argument(
        "--no-preempt-save", action="store_true",
        help="disable the SIGTERM/SIGINT preemption guard that is armed "
        "whenever --checkpoint-dir is set: by default an eviction signal "
        "drains the async checkpoint writer, commits one final "
        "checkpoint, and exits resumable instead of dying mid-iteration",
    )
    t.add_argument(
        "--checkpoint-journal", default=None,
        help="journal factor checkpoints through the transport instead of "
        "the npz --checkpoint-dir: a directory (FileBroker journal) or "
        "tcp://HOST:PORT (cfk_broker server); factors travel as "
        "FeatureRecord wire frames on per-iteration topics, resume replays "
        "the latest committed iteration",
    )
    t.add_argument("--journal-partitions", type=int, default=1)
    t.add_argument(
        "--dataset-cache", default=None,
        help="directory for the built-blocks cache: loaded if present and "
        "its stored build key (data path/size/mtime + layout flags) matches, "
        "rebuilt and overwritten otherwise",
    )
    t.add_argument("--profile-dir", default=None, help="write a jax.profiler trace")
    t.add_argument(
        "--trace-dir", default=None, metavar="DIR",
        help="write the host span trace (Chrome-trace JSON) here at exit; "
        "pass the same directory as --profile-dir to line the host "
        "timeline up with the jax-profiler device trace",
    )
    t.add_argument(
        "--metrics-jsonl", default=None, metavar="PATH",
        help="stream periodic metrics-registry snapshots (one JSON line "
        "per interval) for live dashboards",
    )
    t.add_argument(
        "--metrics-interval-s", type=float, default=10.0,
        help="seconds between --metrics-jsonl snapshots",
    )
    t.add_argument(
        "--output", default="auto",
        help="'auto' = predictions/prediction_matrix_<ts>, 'none', or a path",
    )
    t.add_argument("--metrics", choices=["json", "logfmt"], default="logfmt")
    t.set_defaults(fn=_train)

    e = sub.add_parser("evaluate", help="offline MSE/RMSE of a prediction CSV")
    e.add_argument("ratings_file")
    e.add_argument("prediction_csv")
    e.set_defaults(fn=_evaluate)

    rc = sub.add_parser(
        "recommend", help="top-K recommendations from checkpointed factors"
    )
    rc.add_argument("--checkpoint-dir", default=None)
    rc.add_argument("--checkpoint-journal", default=None,
                    help="serve from a transport journal instead "
                    "(directory or tcp://HOST:PORT)")
    rc.add_argument("--data", required=True,
                    help="training data file (raw-id mapping + exclude-seen)")
    rc.add_argument("--format", choices=["netflix", "movielens"], default="netflix")
    rc.add_argument("--min-rating", type=float, default=0.0)
    rc.add_argument("--users", required=True,
                    help="comma-separated raw user ids, or 'all'")
    rc.add_argument("-k", type=int, default=10)
    rc.add_argument("--include-seen", action="store_true",
                    help="do not exclude already-rated movies")
    rc.set_defaults(fn=_recommend)

    sv = sub.add_parser(
        "serve",
        help="top-K request server: score+top-K kernel over the transport "
        "log, batching/coalescing, hot-user cache (ISSUE 8)",
    )
    sv.add_argument("--checkpoint-dir", default=None)
    sv.add_argument("--checkpoint-journal", default=None,
                    help="serve from a transport journal instead "
                    "(directory or tcp://HOST:PORT)")
    sv.add_argument("--data", required=True,
                    help="training data file (raw-id mapping + exclude-seen)")
    sv.add_argument("--format", choices=["netflix", "movielens"],
                    default="netflix")
    sv.add_argument("--min-rating", type=float, default=0.0)
    sv.add_argument("--broker", default=None, metavar="tcp://HOST:PORT",
                    help="join this native broker's serve topics and "
                    "answer until killed; omit for the built-in "
                    "open-loop loadgen against an in-memory log")
    sv.add_argument("-k", type=int, default=10,
                    help="loadgen-mode top-K per request")
    sv.add_argument("--include-seen", action="store_true",
                    help="do not exclude already-rated movies")
    sv.add_argument("--table-dtype",
                    choices=["float32", "bfloat16", "int8"],
                    default="float32",
                    help="item-table quantization (ops.quant): bf16 "
                    "halves the per-batch table scan, int8+scale "
                    "quarters it")
    sv.add_argument("--tile-m", type=int, default=2048,
                    help="movie-axis tile rows streamed through VMEM")
    sv.add_argument("--serve-mode", choices=["exact", "two_stage"],
                    default="exact",
                    help="retrieval mode (ISSUE 16): two_stage probes a "
                    "k-means centroid index and exactly rescores only "
                    "the probed clusters' rows — the exact scan stays "
                    "the un-disableable fallback")
    sv.add_argument("--clusters", type=int, default=0,
                    help="two_stage k-means cluster count "
                    "(0 = auto ~sqrt(movies))")
    sv.add_argument("--probe-clusters", type=int, default=0,
                    help="clusters probed per user (0 = auto at the "
                    "0.95 modeled recall floor)")
    sv.add_argument("--max-batch", type=int, default=256,
                    help="max requests coalesced into one scoring batch")
    sv.add_argument("--replicas", type=int, default=1,
                    help="serving fleet size (ISSUE 18): N replicas "
                    "behind the request log with user-keyed routing, "
                    "per-replica /metrics + /readyz, admission control, "
                    "and kill/failover at the committed cursor")
    sv.add_argument("--admission-queue", type=int, default=0,
                    help="fleet admission-control queue depth per poll "
                    "(0 = unbounded); backlog beyond it is answered "
                    "with explicit RETRIABLE rejections, never dropped")
    sv.add_argument("--request-partitions", type=int, default=1)
    sv.add_argument("--response-partitions", type=int, default=1)
    sv.add_argument("--loadgen-qps", type=float, default=100.0)
    sv.add_argument("--loadgen-requests", type=int, default=256)
    sv.add_argument("--seed", type=int, default=0)
    sv.add_argument("--compile-cache-dir", default=None, metavar="DIR",
                    help="persistent jax compilation cache keyed per "
                    "device fingerprint (ISSUE 13) — a restarted server "
                    "replays its prewarmed serve programs instead of "
                    "recompiling the batch-bucket set")
    sv.add_argument("--metrics-port", type=int, default=None,
                    help="serve GET /metrics (Prometheus text) on this "
                    "port while the server runs (0 = ephemeral)")
    sv.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="write the host span trace (batch assemble/"
                    "compute/respond timeline) here at exit")
    sv.set_defaults(fn=_serve)

    pd = sub.add_parser(
        "predict",
        help="dump the prediction CSV from checkpointed factors "
        "(the reference's final-collection phase as a standalone step)",
    )
    pd.add_argument("--checkpoint-dir", default=None)
    pd.add_argument("--checkpoint-journal", default=None,
                    help="serve from a transport journal instead "
                    "(directory or tcp://HOST:PORT)")
    pd.add_argument("--data", required=True,
                    help="training data file (raw-id mapping / matrix shape)")
    pd.add_argument("--format", choices=["netflix", "movielens"], default="netflix")
    pd.add_argument("--min-rating", type=float, default=0.0)
    pd.add_argument(
        "--output", default="auto",
        help="'auto' = predictions/prediction_matrix_<ts>, or a path",
    )
    pd.set_defaults(fn=_predict)

    b = sub.add_parser(
        "broker", help="run the native TCP log broker (native/cfk_broker)"
    )
    b.add_argument("--port", type=int, default=29092,
                   help="0 picks an ephemeral port (printed on stdout)")
    b.add_argument("--data-dir", default=None,
                   help="persist logs here (FileBroker-compatible format); "
                   "default is memory-only")
    b.add_argument("--bind", default="127.0.0.1",
                   help="listen address; 0.0.0.0 accepts cross-host clients")
    b.set_defaults(fn=_broker)

    tp = sub.add_parser(
        "topics", help="broker topic admin (the reference's setup.sh role)"
    )
    tp.add_argument("action", choices=["list", "create", "delete", "recreate"])
    tp.add_argument("--broker", required=True,
                    help="tcp://HOST:PORT (list) or tcp://HOST:PORT/TOPIC")
    tp.add_argument("--partitions", type=int, default=4)
    tp.set_defaults(fn=_topics)

    pr = sub.add_parser(
        "produce", help="stream a Netflix-format ratings file into a broker"
    )
    pr.add_argument("--broker", required=True, help="tcp://HOST:PORT[/TOPIC]")
    pr.add_argument("--data", required=True)
    pr.add_argument("--partitions", type=int, default=4)
    pr.add_argument("--append", action="store_true",
                    help="produce into an existing topic (only sound if every "
                    "earlier produce used --no-eof; EOF means end-of-ingest)")
    pr.add_argument("--no-eof", action="store_true",
                    help="skip the EOF fan-out, leaving the topic open for "
                    "more files; the final produce must omit this flag")
    pr.set_defaults(fn=_produce)

    st = sub.add_parser(
        "stream",
        help="exactly-once streaming fold-in: consume rating updates and "
        "fold them into live factors (rate → fold-in → resume)",
    )
    st.add_argument("--data", required=True,
                    help="base ratings (the training corpus the stream "
                    "updates; also the crash replay's state seed)")
    st.add_argument("--format", choices=["netflix", "movielens"],
                    default="netflix")
    st.add_argument("--min-rating", type=float, default=0.0)
    st.add_argument("--updates", required=True,
                    help="the durable updates topic's home: a FileBroker "
                    "directory or tcp://HOST:PORT (cfk_broker server)")
    st.add_argument("--stream-dir", required=True,
                    help="checkpoint store for the atomic factor+cursor "
                    "commits; re-run with the same dir to resume")
    st.add_argument("--produce-csv", default=None, metavar="FILE",
                    help="producer mode: append 'user,movie,rating' lines "
                    "from FILE to the updates topic and exit")
    st.add_argument("--partitions", type=int, default=1,
                    help="updates-topic partitions when creating it "
                    "(--produce-csv on a fresh topic)")
    st.add_argument("--rank", type=int, default=5)
    st.add_argument("--lam", type=float, default=0.05)
    st.add_argument("--iterations", type=int, default=7,
                    help="base-train / warm-retrain iteration count")
    st.add_argument("--seed", type=int, default=42)
    st.add_argument("--layout", choices=["padded", "tiled"],
                    default="padded",
                    help="base dataset layout; also the fold-in default "
                    "(tiled runs the at-scale fused kernels)")
    st.add_argument("--foldin-layout", choices=["auto", "padded", "tiled"],
                    default="auto",
                    help="fold-in solve layout ('auto' follows --layout)")
    st.add_argument("--solver", choices=["auto", "cholesky", "pallas"],
                    default="auto")
    st.add_argument("--dtype", choices=["float32", "bfloat16"],
                    default="float32")
    st.add_argument("--chunk-elems", type=int, default=1 << 20)
    st.add_argument("--batch-records", type=int, default=256,
                    help="log records per partition per micro-batch; part "
                    "of the replay contract (committed with the cursor)")
    st.add_argument("--max-batches", type=int, default=None,
                    help="stop after N micro-batches (default: drain)")
    st.add_argument("--follow", action="store_true",
                    help="keep polling an idle topic instead of exiting "
                    "when caught up")
    st.add_argument("--retrain-every", type=int, default=None, metavar="N",
                    help="warm full retrain (movie side included) every N "
                    "stream commits, current factors as the seed")
    st.add_argument("--health-check-every", type=int, default=1,
                    help="probe every fold-in batch before commit "
                    "(default 1; the ladder escalates on trips and "
                    "quarantines batches that defeat it)")
    st.add_argument("--health-norm-limit", type=float, default=1e6)
    st.add_argument("--max-recoveries", type=int, default=4)
    st.add_argument("--lam-escalation", type=float, default=10.0)
    st.add_argument("--on-unrecoverable", choices=["degrade", "raise"],
                    default="degrade")
    st.add_argument("--keep-last-n", type=int, default=8,
                    help="stream commits retained (per-batch commits grow "
                    "fast; default 8, None-like large values keep more)")
    st.add_argument("--no-preempt-save", action="store_true")
    st.add_argument("--prewarm", action="store_true",
                    help="trace the fold-in pow2 bucket grid before the "
                    "first batch (ISSUE 13): the first real micro-batch "
                    "then pays zero jit traces (padded fold layout; "
                    "pair with --compile-cache-dir so a warm restart "
                    "skips the compiles too)")
    st.add_argument("--compile-cache-dir", default=None, metavar="DIR",
                    help="persistent jax compilation cache keyed per "
                    "device fingerprint — removes the cold-process "
                    "re-compile cost of the fold-in/retrain programs")
    st.add_argument("--metrics-port", type=int, default=None,
                    help="serve GET /metrics (Prometheus text) on this "
                    "port while the stream runs (0 = ephemeral)")
    st.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="write the host span trace (stream batch stage/"
                    "solve/probe/commit timeline) here at exit")
    st.add_argument("--no-eval", action="store_true",
                    help="skip the merged-state RMSE evaluation at exit")
    st.add_argument("--dataset-cache", default=None)
    st.add_argument("--metrics", choices=["json", "logfmt"],
                    default="logfmt")
    st.set_defaults(fn=_stream)

    pl = sub.add_parser(
        "plan",
        help="resolve the execution plan for a shape/device: print the "
        "cost-model choice with per-knob explanations (--explain) or "
        "warm the autotune cache offline (--autotune)",
    )
    pl.add_argument("--users", type=int, default=480_189)
    pl.add_argument("--movies", type=int, default=17_770)
    pl.add_argument("--ratings", type=int, default=100_480_507,
                    help="nnz of the training corpus")
    pl.add_argument("--rank", type=int, default=64)
    pl.add_argument("--shards", type=int, default=1)
    pl.add_argument("--implicit", action="store_true",
                    help="iALS shape (adds the global Gram term)")
    pl.add_argument("--storage-dtype", choices=["float32", "bfloat16"],
                    default="float32",
                    help="factor storage dtype of the run being planned")
    pl.add_argument("--serve", action="store_true",
                    help="plan the top-K serving path instead of training "
                    "(batch quantum + table dtype from the table-scan "
                    "byte model)")
    pl.add_argument("--serve-k", type=int, default=100)
    pl.add_argument("--serve-mode", default="auto",
                    choices=["auto", "exact", "two_stage"],
                    help="retrieval-mode pin of the serve plan "
                    "(ISSUE 16): the byte model weighs the exact scan "
                    "against centroid-probe + expected-shortlist bytes; "
                    "a pinned two_stage whose modeled recall@K falls "
                    "below the 0.95 floor raises at resolution")
    pl.add_argument("--clusters", type=int, default=None, metavar="C",
                    help="two_stage cluster-count pin (0 = exact-only)")
    pl.add_argument("--probe-clusters", type=int, default=None,
                    metavar="P",
                    help="clusters-probed-per-user pin (~0.75*sqrt(C) "
                    "reaches the recall floor)")
    # Constraint pins — 'auto' leaves the knob to the resolver; anything
    # else pins it exactly like the matching ALSConfig/train flag would.
    pl.add_argument("--layout", default="auto",
                    choices=["auto", "padded", "bucketed", "segment",
                             "tiled"])
    pl.add_argument("--exchange", default="auto",
                    choices=["auto", "all_gather", "ring", "hier_ring"])
    pl.add_argument("--table-dtype", default="auto",
                    choices=["auto", "float32", "bfloat16", "int8"])
    pl.add_argument("--fused", default="auto",
                    choices=["auto", "on", "off"])
    pl.add_argument("--gather", default="auto",
                    choices=["auto", "fused", "xla"])
    pl.add_argument("--overlap", default="auto",
                    choices=["auto", "on", "off"])
    pl.add_argument("--reg-solve-algo", default="auto",
                    choices=["auto", "lu", "gj"])
    pl.add_argument("--solver", default="auto",
                    choices=["auto", "cholesky", "pallas"])
    pl.add_argument("--chunk-elems", type=int, default=None)
    pl.add_argument("--offload-tier", default="auto",
                    choices=["auto", "device", "host_window"],
                    help="out-of-core tier pin (ISSUE 11/12): 'auto' "
                    "lets the PER-SHARD memory-budget predicate decide; "
                    "'device' REFUSES when the resident tables cannot "
                    "fit one device; 'host_window' pins the windowed "
                    "host-offload path (sharded shapes pair it with any "
                    "exchange)")
    pl.add_argument("--ici-group", type=int, default=None, metavar="I",
                    help="inner-ring size pin of the hier_ring exchange "
                    "(a real plan field since ISSUE 12 — the cost model "
                    "prices the pinned hierarchy; default: the device's "
                    "ICI domain)")
    pl.add_argument("--staging", default="auto",
                    choices=["auto", "pool", "serial"],
                    help="host staging engine pin of the host_window "
                    "tier (ISSUE 13): the cost model exposes only the "
                    "PCIe share the chosen engine cannot hide")
    pl.add_argument("--hot-rows", type=int, default=None, metavar="F",
                    help="hot-row device cache pin of the host_window "
                    "tier (ISSUE 15): total top-referenced rows kept "
                    "device-resident (0 = off).  Default: free — the "
                    "resolver picks the ~10%% power-law target when the "
                    "budget headroom admits the reservation, else 0; "
                    "--explain prints the decision (admitted bytes vs "
                    "the coverage target), and a pinned-impossible F "
                    "raises naming the bytes")
    pl.add_argument("--device", default="auto",
                    choices=["auto", "v5e", "cpu"],
                    help="'auto' detects the current jax backend; 'v5e' "
                    "plans for the reference TPU without one attached")
    pl.add_argument("--mode", default="model",
                    choices=["model", "pinned", "autotune"])
    pl.add_argument("--explain", action="store_true",
                    help="per-knob cost-model explanation: every cost "
                    "term plus the estimated delta of flipping each free "
                    "knob away from the chosen value")
    pl.add_argument("--autotune", action="store_true",
                    help="measure the top candidates on a trimmed "
                    "synthetic workload and cache the winner (implies "
                    "--mode autotune)")
    pl.add_argument("--cache-path", default=None,
                    help="autotune cache file (default "
                    "~/.cache/cfk_tpu/plan_cache.json)")
    pl.set_defaults(fn=_plan_cmd)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform != "default":
        # Must go through jax.config (some environments force-register a
        # platform and override the JAX_PLATFORMS env var).
        import jax

        jax.config.update("jax_platforms", args.platform)
    from cfk_tpu.resilience.policy import TrainingDivergedError
    from cfk_tpu.transport.tcp import BrokerRequestError

    try:
        return args.fn(args)
    except (ValueError, OSError, KeyError, BrokerRequestError,
            TrainingDivergedError) as e:
        # User-input errors get one clean line; CFK_TPU_TRACEBACK=1 re-raises
        # for debugging.
        import os

        if os.environ.get("CFK_TPU_TRACEBACK"):
            raise
        _eprint(f"error: {e}")
        return 1


if __name__ == "__main__":
    sys.exit(main())
