"""Wire-format codecs, byte-compatible with the reference's hand-rolled serdes.

The reference frames everything big-endian via ``DataOutputStream``
(SURVEY.md §2.3) with no schema registry:

- ``IdRatingPairMessage``: int32 id + int16 rating — 6 bytes
  (``serdes/IdRatingPairMessage/IdRatingPairMessageSerializer.java:23-32``).
  ``id == -1`` is the EOF control message and ``rating`` then carries the
  sender's partition id (``processors/MRatings2BlocksProcessor.java:41``).
- ``FeatureMessage``: int32 id ‖ int32 count + int32 dependentIds ‖
  int32 len + float32 features
  (``serdes/FeatureMessage/FeatureMessageSerializer.java:27-37``).
- float[] : int32 length + float32s (``serdes/FloatArray/FloatArraySerializer.java:14-25``).
- List<Integer>: int32 size + int32s (``serdes/List/ListSerializer.java``).

Unlike the reference's deserializer — which derives the dependentIds length
from a global NUM_FEATURES static
(``serdes/FeatureMessage/FeatureMessageDeserializer.java:32-49``) — these
codecs trust the embedded counts, so they decode any rank without globals.
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

EOF_ID = -1

_ID_RATING = struct.Struct(">ih")  # int32 id, int16 rating
_I32 = struct.Struct(">i")
# RatingUpdate: int64 seq | int64 user | int64 movie | float32 rating.
# A superset of IdRatingPair for the streaming fold-in path: the rating is
# float (re-rates and synthetic streams are not star-quantized) and the
# producer-assigned sequence number is what makes replayed/duplicated
# delivery idempotent (last-seq-wins per (user, movie) cell).
_RATING_UPDATE = struct.Struct(">qqqf")


@dataclasses.dataclass(frozen=True)
class IdRatingPair:
    """A (id, rating) record; ``id == EOF_ID`` marks the EOF control message,
    with ``rating`` carrying the sending partition index."""

    id: int
    rating: int

    @property
    def is_eof(self) -> bool:
        return self.id == EOF_ID


def encode_id_rating(msg: IdRatingPair) -> bytes:
    return _ID_RATING.pack(msg.id, msg.rating)


def decode_id_rating(data: bytes) -> IdRatingPair:
    if len(data) != _ID_RATING.size:
        raise ValueError(f"IdRatingPair frame must be 6 bytes, got {len(data)}")
    id_, rating = _ID_RATING.unpack(data)
    return IdRatingPair(id=id_, rating=rating)


@dataclasses.dataclass(frozen=True)
class RatingUpdate:
    """One streaming rating upsert: user re-/rates movie.

    ``seq`` is assigned by the producer, strictly increasing per logical
    update (``cfk_tpu.streaming.StreamProducer``): when the same (user,
    movie) cell is written twice, the higher ``seq`` wins regardless of
    delivery order, and a retried append (same seq twice in the log) is a
    no-op on the second application — the idempotency key of the fold-in
    pipeline.  Ids are RAW external ids (the partition key is the user id,
    mod-N — same ``PureModPartitioner`` rule as ingest).
    """

    seq: int
    user: int
    movie: int
    rating: float


def encode_rating_update(msg: RatingUpdate) -> bytes:
    return _RATING_UPDATE.pack(msg.seq, msg.user, msg.movie, msg.rating)


def decode_rating_update(data: bytes) -> RatingUpdate:
    if len(data) != _RATING_UPDATE.size:
        raise ValueError(
            f"RatingUpdate frame must be {_RATING_UPDATE.size} bytes, "
            f"got {len(data)}"
        )
    seq, user, movie, rating = _RATING_UPDATE.unpack(data)
    return RatingUpdate(seq=seq, user=user, movie=movie, rating=rating)


# ScoreRequest: int64 req_id | int64 user | int32 k | int32 reply_partition.
# The serving path's query frame (ISSUE 8): ``user`` is a user id in the
# server's id space (dense row for the in-process engine; the CLI resolves
# raw ids before producing), ``k`` the requested top-K, ``reply_partition``
# the response-topic partition this client consumes (one partition per
# client, so responses need no broker-side routing beyond the partition).
_SCORE_REQUEST = struct.Struct(">qqii")


@dataclasses.dataclass(frozen=True)
class ScoreRequest:
    """One top-K query in flight: ``req_id`` is client-assigned and echoed
    on the response — the client's latency clock and dedup key."""

    req_id: int
    user: int
    k: int
    reply_partition: int = 0


def encode_score_request(msg: ScoreRequest) -> bytes:
    return _SCORE_REQUEST.pack(msg.req_id, msg.user, msg.k,
                               msg.reply_partition)


def decode_score_request(data: bytes) -> ScoreRequest:
    if len(data) != _SCORE_REQUEST.size:
        raise ValueError(
            f"ScoreRequest frame must be {_SCORE_REQUEST.size} bytes, "
            f"got {len(data)}"
        )
    req_id, user, k, reply = _SCORE_REQUEST.unpack(data)
    return ScoreRequest(req_id=req_id, user=user, k=k, reply_partition=reply)


# ScoreResponse header: int64 req_id | int32 n | uint16 error_len |
# uint8 flags | int32 epoch | int32 staleness — 23 bytes, then the error
# text and the parallel >i4/>f4 arrays.  ``flags`` bit0 = RETRIABLE: the
# request was refused by admission control (overload shed), not by
# validation — the client may re-send it, unlike a permanent error.
# ``epoch``/``staleness`` (ISSUE 18) stamp every answer with the factor
# table's epoch and the serving replica's delta-log backlog at score
# time — the per-response staleness bound of the fleet contract.
_SCORE_RESPONSE_HDR = struct.Struct(">qiHBii")
_FLAG_RETRIABLE = 0x01


@dataclasses.dataclass(frozen=True)
class ScoreResponse:
    """Top-K answer: parallel (movie row, score) arrays, ids −1-padded when
    fewer than K candidates exist (the kernel's empty-slot convention).
    ``error`` non-empty marks a refused request — ids/scores are then
    empty; ``retriable`` distinguishes an admission-control shed (re-send
    later) from a permanent refusal (unknown user, bad k).  ``epoch`` is
    the factor-table epoch that scored the answer and ``staleness`` the
    replica's unapplied delta backlog at score time (frames)."""

    req_id: int
    movie_rows: np.ndarray  # int32 [k]
    scores: np.ndarray  # float32 [k]
    error: str = ""
    retriable: bool = False
    epoch: int = 0
    staleness: int = 0


def encode_score_response(msg: ScoreResponse) -> bytes:
    ids = np.ascontiguousarray(msg.movie_rows, dtype=">i4")
    sc = np.ascontiguousarray(msg.scores, dtype=">f4")
    if ids.shape != sc.shape or ids.ndim != 1:
        raise ValueError(
            f"parallel 1-D arrays required, got {ids.shape}/{sc.shape}"
        )
    err = msg.error.encode()
    flags = _FLAG_RETRIABLE if msg.retriable else 0
    return (_SCORE_RESPONSE_HDR.pack(msg.req_id, ids.shape[0], len(err),
                                     flags, msg.epoch, msg.staleness)
            + err + ids.tobytes() + sc.tobytes())


def decode_score_response(data: bytes) -> ScoreResponse:
    hdr = _SCORE_RESPONSE_HDR.size
    if len(data) < hdr:
        raise ValueError(f"ScoreResponse frame truncated at {len(data)} bytes")
    req_id, n, elen, flags, epoch, staleness = _SCORE_RESPONSE_HDR.unpack_from(
        data, 0
    )
    off = hdr
    if n < 0 or off + elen + 8 * n != len(data):
        raise ValueError(
            f"corrupt ScoreResponse frame: count {n}, error len {elen}, "
            f"{len(data)} bytes"
        )
    err = data[off : off + elen].decode("utf-8", "replace")
    off += elen
    ids = np.frombuffer(data, dtype=">i4", count=n, offset=off).astype(np.int32)
    off += 4 * n
    sc = np.frombuffer(data, dtype=">f4", count=n, offset=off).astype(np.float32)
    return ScoreResponse(req_id=req_id, movie_rows=ids, scores=sc, error=err,
                         retriable=bool(flags & _FLAG_RETRIABLE),
                         epoch=epoch, staleness=staleness)


@dataclasses.dataclass(frozen=True)
class FeatureRecord:
    """A factor vector in flight, tagged with destination-side dependent rows
    (the analog of ``messages/FeatureMessage.java:6-24`` — immutable here;
    the reference mutates + re-forwards one object per target partition)."""

    id: int
    dependent_ids: tuple[int, ...]
    features: np.ndarray  # float32 [k]


def encode_feature(msg: FeatureRecord) -> bytes:
    feats = np.ascontiguousarray(msg.features, dtype=">f4")
    out = bytearray()
    out += _I32.pack(msg.id)
    out += _I32.pack(len(msg.dependent_ids))
    out += np.asarray(msg.dependent_ids, dtype=">i4").tobytes()
    out += _I32.pack(feats.shape[0])
    out += feats.tobytes()
    return bytes(out)


def _read_i32(data: bytes, off: int, what: str) -> int:
    """int32 read with a ValueError (not struct.error) on truncation, keeping
    the module's corrupt-frame → ValueError contract for all decoders."""
    if off + 4 > len(data):
        raise ValueError(f"corrupt {what}: truncated at byte {off} of {len(data)}")
    return _I32.unpack_from(data, off)[0]


def decode_feature(data: bytes) -> FeatureRecord:
    off = 0
    id_ = _read_i32(data, off, "FeatureRecord")
    off += 4
    ndep = _read_i32(data, off, "FeatureRecord")
    off += 4
    if ndep < 0 or off + 4 * ndep > len(data):
        raise ValueError(f"corrupt FeatureRecord: dependent count {ndep}")
    dep = np.frombuffer(data, dtype=">i4", count=ndep, offset=off)
    off += 4 * ndep
    nfeat = _read_i32(data, off, "FeatureRecord")
    off += 4
    if nfeat < 0 or off + 4 * nfeat != len(data):
        raise ValueError(f"corrupt FeatureRecord: feature count {nfeat}")
    feats = np.frombuffer(data, dtype=">f4", count=nfeat, offset=off)
    return FeatureRecord(
        id=id_,
        dependent_ids=tuple(int(x) for x in dep),
        features=feats.astype(np.float32),
    )


def encode_float_array(arr: np.ndarray) -> bytes:
    a = np.ascontiguousarray(arr, dtype=">f4")
    return _I32.pack(a.shape[0]) + a.tobytes()


def decode_float_array(data: bytes) -> np.ndarray:
    n = _read_i32(data, 0, "float array frame")
    if n < 0 or 4 + 4 * n != len(data):
        raise ValueError(f"corrupt float array frame: count {n}, {len(data)} bytes")
    return np.frombuffer(data, dtype=">f4", count=n, offset=4).astype(np.float32)


# FactorDelta header (ISSUE 18): int32 epoch | int64 seq | uint8 kind |
# int32 num_users | int32 rank | int32 H (eager user rows) | int32 L
# (lazy user rows) | int32 C (seen cells) | int32 M (movie rows) —
# 37 bytes, then the payload arrays in declaration order.  ``seq`` is
# publisher-assigned, strictly increasing across epochs — the replica's
# gap detector compares consecutive frames' seqs, and a hole means a
# lost delta that only a full epoch-snapshot resync can recover.
_FACTOR_DELTA_HDR = struct.Struct(">iqBiiiiii")

DELTA_KIND_ROWS = 0  # per-commit factor rows + seen cells
DELTA_KIND_EPOCH = 1  # epoch rollover announcement (snapshot in the store)

_DELTA_KIND_NAMES = {DELTA_KIND_ROWS: "rows", DELTA_KIND_EPOCH: "epoch"}
_DELTA_KIND_CODES = {v: k for k, v in _DELTA_KIND_NAMES.items()}


@dataclasses.dataclass(frozen=True)
class FactorDelta:
    """One versioned factor-shipping frame on the durable deltas topic.

    ``kind="rows"`` ships a fold-in commit: ``user_rows``/``user_factors``
    are the EAGER (hot) rows with factors in-frame; ``lazy_user_rows``
    name cold rows whose factors live only in the epoch snapshot store
    (replicas pull them on demand — the PR 14 hot/cold split applied to
    shipping); ``cells`` are the commit's rated (user_row, movie_row)
    seen-list extensions; ``movie_rows``/``movie_factors`` carry item-side
    per-row deltas when the commit re-solved movie rows.
    ``kind="epoch"`` announces a warm-retrain rollover: the full snapshot
    is in the ``SnapshotStore`` under ``epoch``; the frame itself carries
    no factors (a multi-GB table does not belong in one log record)."""

    epoch: int
    seq: int
    kind: str  # "rows" | "epoch"
    num_users: int
    user_rows: np.ndarray  # int32 [H] eager rows
    user_factors: np.ndarray  # float32 [H, k]
    lazy_user_rows: np.ndarray  # int32 [L] cold rows (factors in the store)
    cells: np.ndarray  # int32 [C, 2] (user_row, movie_row)
    movie_rows: np.ndarray  # int32 [M]
    movie_factors: np.ndarray  # float32 [M, k]


def make_factor_delta(epoch: int, seq: int, kind: str = "rows", *,
                      num_users: int = 0, user_rows=(), user_factors=None,
                      lazy_user_rows=(), cells=(), movie_rows=(),
                      movie_factors=None, rank: int = 0) -> FactorDelta:
    """Normalize python lists/arrays into a well-formed ``FactorDelta``
    (contiguous dtypes, consistent rank) — the one constructor the
    publisher uses, so encode never sees ragged input."""
    ur = np.asarray(user_rows, np.int32).reshape(-1)
    uf = (np.zeros((0, rank), np.float32) if user_factors is None
          else np.asarray(user_factors, np.float32).reshape(ur.shape[0], -1))
    mr = np.asarray(movie_rows, np.int32).reshape(-1)
    mf = (np.zeros((0, uf.shape[1] if uf.size else rank), np.float32)
          if movie_factors is None
          else np.asarray(movie_factors, np.float32).reshape(mr.shape[0], -1))
    cl = np.asarray(list(cells), np.int32).reshape(-1, 2)
    return FactorDelta(
        epoch=int(epoch), seq=int(seq), kind=kind, num_users=int(num_users),
        user_rows=ur, user_factors=uf,
        lazy_user_rows=np.asarray(lazy_user_rows, np.int32).reshape(-1),
        cells=cl, movie_rows=mr, movie_factors=mf,
    )


def encode_factor_delta(msg: FactorDelta) -> bytes:
    if msg.kind not in _DELTA_KIND_CODES:
        raise ValueError(f"unknown FactorDelta kind {msg.kind!r}")
    ur = np.ascontiguousarray(msg.user_rows, dtype=">i4")
    uf = np.ascontiguousarray(msg.user_factors, dtype=">f4")
    lz = np.ascontiguousarray(msg.lazy_user_rows, dtype=">i4")
    cl = np.ascontiguousarray(msg.cells, dtype=">i4")
    mr = np.ascontiguousarray(msg.movie_rows, dtype=">i4")
    mf = np.ascontiguousarray(msg.movie_factors, dtype=">f4")
    rank = int(uf.shape[1]) if uf.ndim == 2 and uf.shape[0] else (
        int(mf.shape[1]) if mf.ndim == 2 and mf.shape[0] else 0
    )
    if uf.shape[0] != ur.shape[0] or mf.shape[0] != mr.shape[0]:
        raise ValueError(
            f"rows/factors mismatch: {ur.shape[0]}/{uf.shape[0]} user, "
            f"{mr.shape[0]}/{mf.shape[0]} movie"
        )
    hdr = _FACTOR_DELTA_HDR.pack(
        msg.epoch, msg.seq, _DELTA_KIND_CODES[msg.kind], msg.num_users,
        rank, ur.shape[0], lz.shape[0], cl.shape[0], mr.shape[0],
    )
    return (hdr + ur.tobytes() + uf.tobytes() + lz.tobytes()
            + cl.tobytes() + mr.tobytes() + mf.tobytes())


def decode_factor_delta(data: bytes) -> FactorDelta:
    hdr = _FACTOR_DELTA_HDR.size
    if len(data) < hdr:
        raise ValueError(f"FactorDelta frame truncated at {len(data)} bytes")
    epoch, seq, kind, num_users, rank, h, lz, c, m = (
        _FACTOR_DELTA_HDR.unpack_from(data, 0)
    )
    if kind not in _DELTA_KIND_NAMES:
        raise ValueError(f"corrupt FactorDelta frame: unknown kind {kind}")
    if min(rank, h, lz, c, m) < 0:
        raise ValueError(
            f"corrupt FactorDelta frame: negative count "
            f"(rank {rank}, H {h}, L {lz}, C {c}, M {m})"
        )
    expect = hdr + 4 * h + 4 * h * rank + 4 * lz + 8 * c + 4 * m + 4 * m * rank
    if expect != len(data):
        raise ValueError(
            f"corrupt FactorDelta frame: {len(data)} bytes, "
            f"expected {expect} for (rank {rank}, H {h}, L {lz}, "
            f"C {c}, M {m})"
        )
    off = hdr
    ur = np.frombuffer(data, dtype=">i4", count=h, offset=off)
    off += 4 * h
    uf = np.frombuffer(data, dtype=">f4", count=h * rank, offset=off)
    off += 4 * h * rank
    lzr = np.frombuffer(data, dtype=">i4", count=lz, offset=off)
    off += 4 * lz
    cl = np.frombuffer(data, dtype=">i4", count=2 * c, offset=off)
    off += 8 * c
    mr = np.frombuffer(data, dtype=">i4", count=m, offset=off)
    off += 4 * m
    mf = np.frombuffer(data, dtype=">f4", count=m * rank, offset=off)
    return FactorDelta(
        epoch=epoch, seq=seq, kind=_DELTA_KIND_NAMES[kind],
        num_users=num_users,
        user_rows=ur.astype(np.int32),
        user_factors=uf.astype(np.float32).reshape(h, rank),
        lazy_user_rows=lzr.astype(np.int32),
        cells=cl.astype(np.int32).reshape(c, 2),
        movie_rows=mr.astype(np.int32),
        movie_factors=mf.astype(np.float32).reshape(m, rank),
    )


def encode_int_list(values) -> bytes:
    a = np.asarray(list(values), dtype=">i4")
    return _I32.pack(a.shape[0]) + a.tobytes()


def decode_int_list(data: bytes) -> list[int]:
    n = _read_i32(data, 0, "int list frame")
    if n < 0 or 4 + 4 * n != len(data):
        raise ValueError(f"corrupt int list frame: count {n}, {len(data)} bytes")
    return [int(x) for x in np.frombuffer(data, dtype=">i4", count=n, offset=4)]
