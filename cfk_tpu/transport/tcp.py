"""TCP Transport client for the native broker (``native/cfk_broker.cpp``).

The reference's durable-log service is a Kafka broker reached over TCP
(``apps/BaseKafkaApp.java:19`` hardcodes ``localhost:29092``); this is the
framework's native equivalent — ``TcpBrokerClient`` implements the same
``Transport`` protocol as ``InMemoryBroker``/``FileBroker``, so ingest's
EOF-barrier protocol and the checkpoint journal run unchanged against a
broker *process*, across process and host boundaries.

Throughput comes from batching, the same lever as the reference's Kafka
producer (async sends, unbounded ``buffer.memory``,
``producers/NetflixDataFormatProducer.java:31-33``): ``produce`` buffers
records client-side and ships one PRODUCE_BATCH frame per
``batch_records``/``batch_bytes`` window.  Read-your-writes holds because
every read operation (``consume``/``end_offset``) flushes the buffer first.

Wire protocol: see the header comment of ``native/cfk_broker.cpp``.
"""

from __future__ import annotations

import os
import socket
import struct
import subprocess
import time
from typing import Iterator

from cfk_tpu.transport.broker import Record

_NATIVE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "native")
)
_BROKER_BIN = os.path.join(_NATIVE_DIR, "cfk_broker")

_OP_CREATE_TOPIC = 1
_OP_PRODUCE_BATCH = 2
_OP_FETCH = 3
_OP_NUM_PARTITIONS = 4
_OP_END_OFFSET = 5
_OP_DELETE_TOPIC = 6
_OP_PING = 7
_OP_LIST_TOPICS = 8


class BrokerRequestError(RuntimeError):
    """The broker rejected a request (unknown topic, bad partition, ...)."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = sock.recv(n)
        if not chunk:
            raise ConnectionError("broker closed the connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class TcpBrokerClient:
    """Transport over one TCP connection to a cfk_broker server.

    Not thread-safe (one in-flight request per connection); open one client
    per thread/process, like one Kafka producer per thread.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        batch_records: int = 4096,
        batch_bytes: int = 1 << 20,
        fetch_records: int = 8192,
        fetch_bytes: int = 4 << 20,
    ) -> None:
        self._sock = socket.create_connection((host, port))
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._batch_records = batch_records
        self._batch_bytes = batch_bytes
        self._fetch_records = fetch_records
        self._fetch_bytes = fetch_bytes
        # Pending PRODUCE buffer: topic → (list of encoded records, bytes).
        self._pending: dict[str, list[bytes]] = {}
        self._pending_count = 0
        self._pending_bytes = 0

    # -- request plumbing ---------------------------------------------------

    def _request(self, body: bytes) -> bytes:
        self._sock.sendall(struct.pack(">I", len(body)) + body)
        (blen,) = struct.unpack(">I", _recv_exact(self._sock, 4))
        resp = _recv_exact(self._sock, blen)
        if resp[0] == 0:
            return resp[1:]
        (mlen,) = struct.unpack(">H", resp[1:3])
        message = resp[3 : 3 + mlen].decode("utf-8", "replace")
        if "unknown topic" in message:
            # Same exception type as the in-process Transports, so callers'
            # provision-before-run handling is implementation-agnostic.
            raise KeyError(message)
        raise BrokerRequestError(message)

    @staticmethod
    def _name(topic: str) -> bytes:
        raw = topic.encode()
        return struct.pack(">H", len(raw)) + raw

    # -- Transport protocol -------------------------------------------------

    def create_topic(self, name: str, num_partitions: int) -> None:
        if num_partitions < 1:
            raise ValueError(f"num_partitions must be >= 1, got {num_partitions}")
        try:
            self._request(
                bytes([_OP_CREATE_TOPIC]) + self._name(name)
                + struct.pack(">I", num_partitions)
            )
        except BrokerRequestError as e:
            if "already exists" in str(e):
                raise ValueError(str(e)) from None
            raise

    def delete_topic(self, name: str) -> None:
        self._pending.pop(name, None)
        self._request(bytes([_OP_DELETE_TOPIC]) + self._name(name))

    def produce(
        self, topic: str, key: int, value: bytes, partition: int | None = None
    ) -> None:
        if partition is None and key < 0:
            # Fail on the client, matching mod_partition's contract; the
            # server enforces the same rule.
            raise ValueError(
                f"negative key {key} requires an explicit partition="
            )
        rec = struct.pack(
            ">iiI", -1 if partition is None else partition, key, len(value)
        ) + value
        self._pending.setdefault(topic, []).append(rec)
        self._pending_count += 1
        self._pending_bytes += len(rec)
        if (
            self._pending_count >= self._batch_records
            or self._pending_bytes >= self._batch_bytes
        ):
            self.flush()

    def flush(self) -> None:
        """Ship all buffered records (one PRODUCE_BATCH per topic).

        On a failed request the unsent topics' records are restored to the
        buffer.  The failing topic's own batch is restored only for an
        unknown-topic rejection (KeyError) — the server validates the whole
        batch before appending anything, so "create the topic, flush again"
        loses nothing.  Other rejections (bad partition, malformed record)
        would fail identically on retry, so that batch is dropped with the
        raised error as the caller's signal; a transport failure mid-request
        (ConnectionError) leaves the batch in doubt.
        """
        pending, self._pending = self._pending, {}
        self._pending_count = self._pending_bytes = 0

        def restore(topic):
            restored = self._pending.setdefault(topic, [])
            restored[:0] = pending[topic]
            self._pending_count += len(pending[topic])
            self._pending_bytes += sum(len(r) for r in pending[topic])

        topics = list(pending)
        for i, topic in enumerate(topics):
            recs = pending[topic]
            try:
                self._request(
                    bytes([_OP_PRODUCE_BATCH]) + self._name(topic)
                    + struct.pack(">I", len(recs)) + b"".join(recs)
                )
            except Exception as e:
                if isinstance(e, KeyError):
                    restore(topic)
                for unsent in topics[i + 1:]:
                    restore(unsent)
                raise

    def consume(
        self, topic: str, partition: int, start_offset: int = 0
    ) -> Iterator[Record]:
        self.flush()
        offset = start_offset
        # Snapshot semantics like the other Transports: stop at the log end
        # observed on the FIRST fetch — a concurrent producer must not turn
        # this iterator into an endless tail.
        snapshot_end: int | None = None
        while True:
            resp = self._request(
                bytes([_OP_FETCH]) + self._name(topic)
                + struct.pack(
                    ">IQII", partition, offset,
                    self._fetch_records, self._fetch_bytes,
                )
            )
            log_end, count = struct.unpack(">QI", resp[:12])
            if snapshot_end is None:
                snapshot_end = log_end
            pos = 12
            for _ in range(count):
                key, vlen = struct.unpack(">iI", resp[pos : pos + 8])
                pos += 8
                if offset >= snapshot_end:
                    return
                yield Record(key=key, value=resp[pos : pos + vlen], offset=offset)
                pos += vlen
                offset += 1
            if count == 0 or offset >= snapshot_end:
                return

    def num_partitions(self, topic: str) -> int:
        resp = self._request(bytes([_OP_NUM_PARTITIONS]) + self._name(topic))
        return struct.unpack(">I", resp)[0]

    def end_offset(self, topic: str, partition: int) -> int:
        self.flush()
        resp = self._request(
            bytes([_OP_END_OFFSET]) + self._name(topic)
            + struct.pack(">I", partition)
        )
        return struct.unpack(">Q", resp)[0]

    # -- extras -------------------------------------------------------------

    def ping(self) -> None:
        self._request(bytes([_OP_PING]))

    def topics(self) -> list[str]:
        resp = self._request(bytes([_OP_LIST_TOPICS]))
        (count,) = struct.unpack(">I", resp[:4])
        names, pos = [], 4
        for _ in range(count):
            (nlen,) = struct.unpack(">H", resp[pos : pos + 2])
            pos += 2
            names.append(resp[pos : pos + nlen].decode())
            pos += nlen
        return names

    def close(self) -> None:
        try:
            self.flush()
        finally:
            self._sock.close()

    def __enter__(self) -> "TcpBrokerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def build_broker(quiet: bool = True) -> bool:
    """Compile the broker binary with make; returns availability."""
    if os.path.exists(_BROKER_BIN):
        return True
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "cfk_broker"],
            check=True, capture_output=quiet,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False
    return os.path.exists(_BROKER_BIN)


class BrokerProcess:
    """Spawn a cfk_broker server subprocess and wait until it listens.

    ``port=0`` picks an ephemeral port (read back from the server's
    ``CFK_BROKER LISTENING <port>`` line).  ``data_dir=None`` runs the broker
    memory-only; with a directory, logs persist in the FileBroker on-disk
    format and survive restarts.
    """

    def __init__(
        self, port: int = 0, data_dir: str | None = None, *, timeout: float = 10.0
    ) -> None:
        if not build_broker():
            raise RuntimeError(
                "cfk_broker binary unavailable (make -C native failed)"
            )
        argv = [_BROKER_BIN, str(port)] + ([data_dir] if data_dir else [])
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True
        )
        # select-based wait: readline() alone would block past the timeout
        # if the server wedges before printing its LISTENING line.
        import select

        deadline = time.monotonic() + timeout
        line = ""
        while "LISTENING" not in line:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"cfk_broker exited with {self.proc.returncode}"
                )
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.terminate()
                raise TimeoutError("cfk_broker did not start listening in time")
            ready, _, _ = select.select([self.proc.stdout], [], [], min(remaining, 0.5))
            if ready:
                line = self.proc.stdout.readline()
                if not line:  # EOF: process died without the banner
                    continue
        self.port = int(line.strip().rsplit(" ", 1)[-1])

    def connect(self, **kwargs) -> TcpBrokerClient:
        return TcpBrokerClient("127.0.0.1", self.port, **kwargs)

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()

    def __enter__(self) -> "BrokerProcess":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
