"""TCP Transport client for the native broker (``native/cfk_broker.cpp``).

The reference's durable-log service is a Kafka broker reached over TCP
(``apps/BaseKafkaApp.java:19`` hardcodes ``localhost:29092``); this is the
framework's native equivalent — ``TcpBrokerClient`` implements the same
``Transport`` protocol as ``InMemoryBroker``/``FileBroker``, so ingest's
EOF-barrier protocol and the checkpoint journal run unchanged against a
broker *process*, across process and host boundaries.

Throughput comes from batching, the same lever as the reference's Kafka
producer (async sends, unbounded ``buffer.memory``,
``producers/NetflixDataFormatProducer.java:31-33``): ``produce`` buffers
records client-side and ships one PRODUCE_BATCH frame per
``batch_records``/``batch_bytes`` window.  Read-your-writes holds because
every read operation (``consume``/``end_offset``) flushes the buffer first.

Wire protocol: see the header comment of ``native/cfk_broker.cpp``.
"""

from __future__ import annotations

import os
import socket
import struct
import subprocess
import time
from typing import Iterator

from cfk_tpu.transport.broker import Record

_NATIVE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "native")
)
_BROKER_BIN = os.path.join(_NATIVE_DIR, "cfk_broker")

_OP_CREATE_TOPIC = 1
_OP_PRODUCE_BATCH = 2
_OP_FETCH = 3
_OP_NUM_PARTITIONS = 4
_OP_END_OFFSET = 5
_OP_DELETE_TOPIC = 6
_OP_PING = 7
_OP_LIST_TOPICS = 8

# Keep every request body under the server's 64 MiB frame cap (cfk_broker's
# kMaxBodyLen) with headroom for the op/name/count framing; the server closes
# the connection on an oversized frame rather than answering with an error.
_MAX_BATCH_BYTES = (64 << 20) - 4096


class BrokerRequestError(RuntimeError):
    """The broker rejected a request (unknown topic, bad partition, ...)."""


def _recv_exact(sock: socket.socket, n: int, timeouts: int = 0) -> bytes:
    """Read exactly ``n`` bytes; with a socket read timeout set, tolerate
    up to ``timeouts`` CONSECUTIVE timeout windows (a congested broker
    delaying frames is a delay, not a death — the bytes already read stay
    accumulated, and any received chunk resets the window count, so a
    large response making steady slow progress never fails) before
    letting the timeout escape."""
    chunks = []
    waits = 0
    while n > 0:
        try:
            chunk = sock.recv(n)
        except TimeoutError:
            waits += 1
            if waits > timeouts:
                raise
            continue
        if not chunk:
            raise ConnectionError("broker closed the connection")
        waits = 0
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class TcpBrokerClient:
    """Transport over one TCP connection to a cfk_broker server.

    Not thread-safe (one in-flight request per connection); open one client
    per thread/process, like one Kafka producer per thread.

    Connection setup retries with exponential backoff + jitter
    (``cfk_tpu.resilience.retry``): each attempt dials under
    ``connect_timeout`` and then PINGs, so a listener whose accept loop is
    dead or dying (the half-up broker a fixed-interval poll hammers
    forever) is detected and retried instead of wedging the first real
    request.  ``read_timeout`` bounds every response read; up to
    ``read_retries`` consecutive timeout windows are tolerated per read
    (delayed frames — congestion — are waited out, a closed connection
    still fails fast).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        batch_records: int = 4096,
        batch_bytes: int = 1 << 20,
        fetch_records: int = 8192,
        fetch_bytes: int = 4 << 20,
        connect_timeout: float = 5.0,
        connect_retries: int = 3,
        retry_base: float = 0.05,
        read_timeout: float | None = None,
        read_retries: int = 3,
    ) -> None:
        from cfk_tpu.resilience.retry import retry_call

        def dial() -> socket.socket:
            sock = socket.create_connection(
                (host, port), timeout=connect_timeout
            )
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # Liveness handshake: a PING proves the broker's serving
                # loop (not just its accept backlog) is up — a dropped
                # connection surfaces here, inside the retry, instead of
                # poisoning the caller's first real request.
                sock.sendall(struct.pack(">I", 1) + bytes([_OP_PING]))
                (blen,) = struct.unpack(">I", _recv_exact(sock, 4))
                _recv_exact(sock, blen)
                return sock
            except BaseException:
                sock.close()
                raise
        self._sock = retry_call(
            dial,
            retries=connect_retries,
            retry_on=(OSError,),
            base=retry_base,
            describe=f"connect to broker {host}:{port}",
        )
        self._sock.settimeout(read_timeout)
        self._read_retries = read_retries
        self._batch_records = batch_records
        self._batch_bytes = batch_bytes
        self._fetch_records = fetch_records
        self._fetch_bytes = fetch_bytes
        # Pending PRODUCE buffer: topic → (list of encoded records, bytes).
        self._pending: dict[str, list[bytes]] = {}
        self._pending_count = 0
        self._pending_bytes = 0

    # -- request plumbing ---------------------------------------------------

    def _request(self, body: bytes) -> bytes:
        # A timeout or transport error that escapes mid-frame leaves the
        # stream desynced (a later read would parse leftover payload
        # bytes as a length header) — the connection is unusable, so
        # close it and fail every subsequent request loudly instead of
        # silently mis-framing.
        try:
            self._sock.sendall(struct.pack(">I", len(body)) + body)
            (blen,) = struct.unpack(
                ">I", _recv_exact(self._sock, 4, self._read_retries)
            )
            resp = _recv_exact(self._sock, blen, self._read_retries)
        except (TimeoutError, ConnectionError, OSError):
            self._sock.close()
            raise
        if resp[0] == 0:
            return resp[1:]
        (mlen,) = struct.unpack(">H", resp[1:3])
        message = resp[3 : 3 + mlen].decode("utf-8", "replace")
        if "unknown topic" in message:
            # Same exception type as the in-process Transports, so callers'
            # provision-before-run handling is implementation-agnostic.
            raise KeyError(message)
        raise BrokerRequestError(message)

    @staticmethod
    def _name(topic: str) -> bytes:
        raw = topic.encode()
        if len(raw) > 249:  # Kafka's own topic-name limit; also keeps the
            # name framing inside _MAX_BATCH_BYTES's request-frame headroom.
            raise ValueError(f"topic name too long ({len(raw)} bytes, max 249)")
        return struct.pack(">H", len(raw)) + raw

    # -- Transport protocol -------------------------------------------------

    def create_topic(self, name: str, num_partitions: int) -> None:
        if num_partitions < 1:
            raise ValueError(f"num_partitions must be >= 1, got {num_partitions}")
        try:
            self._request(
                bytes([_OP_CREATE_TOPIC]) + self._name(name)
                + struct.pack(">I", num_partitions)
            )
        except BrokerRequestError as e:
            if "already exists" in str(e):
                raise ValueError(str(e)) from None
            raise

    def delete_topic(self, name: str) -> None:
        dropped = self._pending.pop(name, [])
        self._pending_count -= len(dropped)
        self._pending_bytes -= sum(len(r) for r in dropped)
        self._request(bytes([_OP_DELETE_TOPIC]) + self._name(name))

    def produce(
        self, topic: str, key: int, value: bytes, partition: int | None = None
    ) -> None:
        if partition is None and key < 0:
            # Fail on the client, matching mod_partition's contract; the
            # server enforces the same rule.
            raise ValueError(
                f"negative key {key} requires an explicit partition="
            )
        if len(value) > _MAX_BATCH_BYTES:
            # The server closes the connection on an oversized frame with no
            # error response — fail loudly here instead.
            raise ValueError(
                f"record of {len(value)} bytes exceeds the broker's "
                f"{_MAX_BATCH_BYTES}-byte frame budget"
            )
        # Validate the name before buffering: raising at flush time would
        # surface far from the faulty call and drop the sub-batch.
        self._name(topic)
        rec = struct.pack(
            ">iiI", -1 if partition is None else partition, key, len(value)
        ) + value
        self._pending.setdefault(topic, []).append(rec)
        self._pending_count += 1
        self._pending_bytes += len(rec)
        if (
            self._pending_count >= self._batch_records
            or self._pending_bytes >= self._batch_bytes
        ):
            self.flush()

    def flush(self) -> None:
        """Ship all buffered records (PRODUCE_BATCH requests per topic,
        split into sub-batches that fit the server's request frame cap).

        On a failed request the unsent records are restored to the buffer.
        The failing sub-batch itself is restored only for an unknown-topic
        rejection (KeyError) — the server validates the whole batch before
        appending anything, so "create the topic, flush again" loses
        nothing.  Other rejections (bad partition, malformed record) would
        fail identically on retry, so that sub-batch is dropped with the
        raised error as the caller's signal; a transport failure mid-request
        (ConnectionError) leaves it in doubt.
        """
        pending, self._pending = self._pending, {}
        self._pending_count = self._pending_bytes = 0

        def restore(topic, recs):
            if not recs:
                return
            restored = self._pending.setdefault(topic, [])
            restored[:0] = recs
            self._pending_count += len(recs)
            self._pending_bytes += sum(len(r) for r in recs)

        topics = list(pending)
        for i, topic in enumerate(topics):
            recs = pending[topic]
            done = 0
            while done < len(recs):
                end, size = done, 0
                while end < len(recs) and (
                    end == done or size + len(recs[end]) <= _MAX_BATCH_BYTES
                ):
                    size += len(recs[end])
                    end += 1
                chunk = recs[done:end]
                try:
                    self._request(
                        bytes([_OP_PRODUCE_BATCH]) + self._name(topic)
                        + struct.pack(">I", len(chunk)) + b"".join(chunk)
                    )
                except Exception as e:
                    tail = done if isinstance(e, KeyError) else end
                    restore(topic, recs[tail:])
                    for unsent in topics[i + 1:]:
                        restore(unsent, pending[unsent])
                    raise
                done = end

    def consume(
        self, topic: str, partition: int, start_offset: int = 0
    ) -> Iterator[Record]:
        self.flush()
        offset = start_offset
        # Snapshot semantics like the other Transports: stop at the log end
        # observed on the FIRST fetch — a concurrent producer must not turn
        # this iterator into an endless tail.
        snapshot_end: int | None = None
        while True:
            resp = self._request(
                bytes([_OP_FETCH]) + self._name(topic)
                + struct.pack(
                    ">IQII", partition, offset,
                    self._fetch_records, self._fetch_bytes,
                )
            )
            log_end, count = struct.unpack(">QI", resp[:12])
            if snapshot_end is None:
                snapshot_end = log_end
            pos = 12
            for _ in range(count):
                key, vlen = struct.unpack(">iI", resp[pos : pos + 8])
                pos += 8
                if offset >= snapshot_end:
                    return
                yield Record(key=key, value=resp[pos : pos + vlen], offset=offset)
                pos += vlen
                offset += 1
            if count == 0 or offset >= snapshot_end:
                return

    def num_partitions(self, topic: str) -> int:
        resp = self._request(bytes([_OP_NUM_PARTITIONS]) + self._name(topic))
        return struct.unpack(">I", resp)[0]

    def end_offset(self, topic: str, partition: int) -> int:
        self.flush()
        resp = self._request(
            bytes([_OP_END_OFFSET]) + self._name(topic)
            + struct.pack(">I", partition)
        )
        return struct.unpack(">Q", resp)[0]

    # -- extras -------------------------------------------------------------

    def ping(self) -> None:
        self._request(bytes([_OP_PING]))

    def topics(self) -> list[str]:
        resp = self._request(bytes([_OP_LIST_TOPICS]))
        (count,) = struct.unpack(">I", resp[:4])
        names, pos = [], 4
        for _ in range(count):
            (nlen,) = struct.unpack(">H", resp[pos : pos + 2])
            pos += 2
            names.append(resp[pos : pos + nlen].decode())
            pos += nlen
        return names

    def close(self, *, flush: bool = True) -> None:
        try:
            if flush:
                self.flush()
        finally:
            self._sock.close()

    def __enter__(self) -> "TcpBrokerClient":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        # Don't let a failing exit-time flush replace the body's exception.
        self.close(flush=exc_type is None)


def build_broker(quiet: bool = True) -> bool:
    """Compile the broker binary with make (incremental — make itself skips
    an up-to-date binary, so source edits always rebuild); returns
    availability."""
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "cfk_broker"],
            check=True, capture_output=quiet,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        return os.path.exists(_BROKER_BIN)
    return os.path.exists(_BROKER_BIN)


class BrokerProcess:
    """Spawn a cfk_broker server subprocess and wait until it listens.

    ``port=0`` picks an ephemeral port (read back from the server's
    ``CFK_BROKER LISTENING <port>`` line).  ``data_dir=None`` runs the broker
    memory-only; with a directory, logs persist in the FileBroker on-disk
    format and survive restarts.
    """

    def __init__(
        self, port: int = 0, data_dir: str | None = None, *, timeout: float = 10.0
    ) -> None:
        if not build_broker():
            raise RuntimeError(
                "cfk_broker binary unavailable (make -C native failed)"
            )
        argv = [_BROKER_BIN, str(port)] + ([data_dir] if data_dir else [])
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL
        )
        # Raw nonblocking reads under a select deadline: buffered readline()
        # would block past the timeout on a partial line (a wedged server),
        # and select() cannot see data already inside a stdio buffer.
        import select

        from cfk_tpu.resilience.retry import backoff_delays

        # EOF-while-alive poll cadence: jittered exponential backoff
        # instead of the old fixed 0.05 s spin — many workers waiting on
        # one broker no longer wake in lockstep.
        delays = backoff_delays(base=0.02, max_delay=0.25)
        deadline = time.monotonic() + timeout
        fd = self.proc.stdout.fileno()
        os.set_blocking(fd, False)
        buf = b""
        while True:
            nl = buf.find(b"\n")
            if nl >= 0:
                line, buf = buf[:nl], buf[nl + 1:]
                if b"LISTENING" in line:
                    self.port = int(line.strip().rsplit(b" ", 1)[-1])
                    break
                continue
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"cfk_broker exited with {self.proc.returncode}"
                )
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.terminate()
                raise TimeoutError("cfk_broker did not start listening in time")
            ready, _, _ = select.select([fd], [], [], min(remaining, 0.5))
            if ready:
                try:
                    chunk = os.read(fd, 4096)
                except BlockingIOError:
                    chunk = b""
                if chunk:
                    buf += chunk
                else:
                    # EOF while still alive: don't spin on the always-ready
                    # fd; the poll() check above reports the exit.
                    time.sleep(min(next(delays), max(0.0, remaining)))

    def connect(self, **kwargs) -> TcpBrokerClient:
        return TcpBrokerClient("127.0.0.1", self.port, **kwargs)

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()

    def __enter__(self) -> "BrokerProcess":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
