"""Durable file-backed Transport: an append-only partitioned log on disk.

The reference's durability is Kafka's: topics retained unboundedly
(``dev/env/kafka.env`` ``KAFKA_LOG_RETENTION_HOURS=-1``) are the only thing
that survives a crash, and recovery is a from-scratch replay
(``apps/BaseKafkaApp.java:36,55``; SURVEY.md §5).  ``FileBroker`` provides the
same durable-log contract without a broker process: one append-only segment
file per partition, length-prefixed big-endian frames (the framing style of
the reference's hand-rolled serdes, ``serdes/IdRatingPairMessage/*``), torn
trailing writes truncated away on reopen — Kafka-style log recovery.

It implements the same ``Transport`` protocol as ``InMemoryBroker``, so the
ingest EOF-barrier protocol and checkpoint journaling run unchanged on top.
"""

from __future__ import annotations

import json
import os
import shutil
import struct
from typing import Iterator

from cfk_tpu.transport.broker import Record, mod_partition

# Frame: int32 key ‖ uint32 value length ‖ value bytes (big-endian, matching
# the DataOutputStream framing of the reference serdes).
_HEADER = struct.Struct(">iI")
_META = "meta.json"
# Sparse byte index granularity: byte position of every K-th record is kept
# so consume(start_offset=...) seeks near the target instead of decoding the
# whole log (checkpoint-journal resumes read only the tail).
_INDEX_EVERY = 1024


def _log_path(topic_dir: str, partition: int) -> str:
    return os.path.join(topic_dir, f"p{partition:05d}.log")


def _scan_log(path: str) -> tuple[int, int, list[int]]:
    """(record_count, valid_byte_length, sparse_index) of a segment file.

    A torn final frame (partial header or short value — a crash mid-append)
    ends the valid region; everything before it is intact.  ``sparse_index``
    holds the byte position of record i·_INDEX_EVERY.
    """
    count = 0
    pos = 0
    index: list[int] = []
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        while pos + _HEADER.size <= size:
            header = f.read(_HEADER.size)
            if len(header) < _HEADER.size:
                break
            _, vlen = _HEADER.unpack(header)
            if pos + _HEADER.size + vlen > size:
                break
            if count % _INDEX_EVERY == 0:
                index.append(pos)
            f.seek(vlen, os.SEEK_CUR)
            pos += _HEADER.size + vlen
            count += 1
    return count, pos, index


class FileBroker:
    """On-disk Transport rooted at ``directory``; safe to reopen after a crash.

    ``fsync=True`` fsyncs every append (the durable default for checkpoint
    journals); ``fsync=False`` leaves flushing to the OS page cache — faster
    for bulk ingest, still crash-consistent up to the torn tail.
    """

    def __init__(self, directory: str, *, fsync: bool = True) -> None:
        self.directory = directory
        self._fsync = fsync
        self._files: dict[tuple[str, int], object] = {}
        self._counts: dict[tuple[str, int], int] = {}
        self._bytes: dict[tuple[str, int], int] = {}
        self._index: dict[tuple[str, int], list[int]] = {}
        self._partitions: dict[str, int] = {}
        os.makedirs(directory, exist_ok=True)
        for topic in sorted(os.listdir(directory)):
            meta_path = os.path.join(directory, topic, _META)
            if not os.path.isfile(meta_path):
                continue
            with open(meta_path) as f:
                self._partitions[topic] = int(json.load(f)["num_partitions"])
            for p in range(self._partitions[topic]):
                path = _log_path(os.path.join(directory, topic), p)
                if os.path.exists(path):
                    count, valid, index = _scan_log(path)
                    if valid < os.path.getsize(path):  # torn tail: truncate
                        with open(path, "r+b") as f:
                            f.truncate(valid)
                    self._counts[(topic, p)] = count
                    self._bytes[(topic, p)] = valid
                    self._index[(topic, p)] = index
                else:
                    self._counts[(topic, p)] = 0
                    self._bytes[(topic, p)] = 0
                    self._index[(topic, p)] = []

    # -- Transport protocol -------------------------------------------------

    def create_topic(self, name: str, num_partitions: int) -> None:
        if num_partitions < 1:
            raise ValueError(f"num_partitions must be >= 1, got {num_partitions}")
        if name in self._partitions:
            raise ValueError(f"topic {name!r} already exists")
        if os.sep in name or name.startswith("."):
            raise ValueError(f"invalid topic name {name!r}")
        topic_dir = os.path.join(self.directory, name)
        os.makedirs(topic_dir, exist_ok=True)
        tmp = os.path.join(topic_dir, _META + ".tmp")
        with open(tmp, "w") as f:
            json.dump({"num_partitions": num_partitions}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(topic_dir, _META))
        self._partitions[name] = num_partitions
        for p in range(num_partitions):
            self._counts[(name, p)] = 0
            self._bytes[(name, p)] = 0
            self._index[(name, p)] = []

    def delete_topic(self, name: str) -> None:
        if name not in self._partitions:
            return
        for p in range(self._partitions[name]):
            fh = self._files.pop((name, p), None)
            if fh is not None:
                fh.close()
            self._counts.pop((name, p), None)
            self._bytes.pop((name, p), None)
            self._index.pop((name, p), None)
        del self._partitions[name]
        shutil.rmtree(os.path.join(self.directory, name), ignore_errors=True)

    def _num_partitions_checked(self, topic: str) -> int:
        try:
            return self._partitions[topic]
        except KeyError:
            raise KeyError(
                f"unknown topic {topic!r}; create_topic first (the reference "
                "had the same split: setup.sh provisions topics before the app runs)"
            ) from None

    def produce(
        self, topic: str, key: int, value: bytes, partition: int | None = None
    ) -> None:
        n = self._num_partitions_checked(topic)
        if partition is None:
            partition = mod_partition(key, n)
        if not 0 <= partition < n:
            raise IndexError(f"partition {partition} out of range for {topic!r}")
        fh = self._files.get((topic, partition))
        if fh is None:
            fh = open(_log_path(os.path.join(self.directory, topic), partition), "ab")
            self._files[(topic, partition)] = fh
        record = _HEADER.pack(key, len(value)) + value
        fh.write(record)
        # The seek index is only touched AFTER pack and write both succeed:
        # an entry appended ahead of a failure (key overflow, ENOSPC) would
        # duplicate on retry and silently mislabel every indexed consume.
        if self._counts[(topic, partition)] % _INDEX_EVERY == 0:
            self._index[(topic, partition)].append(self._bytes[(topic, partition)])
        if self._fsync:
            fh.flush()
            os.fsync(fh.fileno())
        self._counts[(topic, partition)] += 1
        self._bytes[(topic, partition)] += _HEADER.size + len(value)

    def produce_frames(
        self, topic: str, keys, frames, partition: int
    ) -> None:
        """Bulk append of n equal-size values in one write syscall.

        ``keys`` is an int array [n], ``frames`` a uint8 array [n, vbytes]
        (each row one record value).  Semantically identical to n ``produce``
        calls; exists because checkpoint journaling appends ~500k factor-row
        frames per iteration and the per-record path would dominate save
        time with Python-loop and syscall overhead.
        """
        import numpy as np

        keys = np.asarray(keys)
        frames = np.asarray(frames, dtype=np.uint8)
        n, vbytes = frames.shape
        if keys.shape != (n,):
            raise ValueError(f"keys shape {keys.shape} != ({n},)")
        if n and (keys.min() < -(2**31) or keys.max() >= 2**31):
            # Match the per-record path, where struct.pack('>i') raises on
            # overflow — astype('>i4') below would silently wrap instead.
            raise OverflowError(
                f"record keys must fit int32, got range "
                f"[{int(keys.min())}, {int(keys.max())}]"
            )
        nparts = self._num_partitions_checked(topic)
        if not 0 <= partition < nparts:
            raise IndexError(f"partition {partition} out of range for {topic!r}")
        fh = self._files.get((topic, partition))
        if fh is None:
            fh = open(_log_path(os.path.join(self.directory, topic), partition), "ab")
            self._files[(topic, partition)] = fh
        blob = np.empty((n, _HEADER.size + vbytes), np.uint8)
        blob[:, 0:4] = (
            np.ascontiguousarray(keys.astype(">i4")).view(np.uint8).reshape(n, 4)
        )
        blob[:, 4:8] = np.frombuffer(np.array(vbytes, ">u4").tobytes(), np.uint8)
        blob[:, 8:] = frames
        base_count = self._counts[(topic, partition)]
        base_bytes = self._bytes[(topic, partition)]
        rec_bytes = _HEADER.size + vbytes
        fh.write(blob.tobytes())
        # Index entries only after the write succeeds (see produce()).
        index = self._index[(topic, partition)]
        first = (-base_count) % _INDEX_EVERY
        for i in range(first, n, _INDEX_EVERY):
            index.append(base_bytes + i * rec_bytes)
        if self._fsync:
            fh.flush()
            os.fsync(fh.fileno())
        self._counts[(topic, partition)] = base_count + n
        self._bytes[(topic, partition)] = base_bytes + n * rec_bytes

    def consume(
        self, topic: str, partition: int, start_offset: int = 0
    ) -> Iterator[Record]:
        self._num_partitions_checked(topic)
        end = self._counts[(topic, partition)]
        fh = self._files.get((topic, partition))
        if fh is not None:
            fh.flush()
        path = _log_path(os.path.join(self.directory, topic), partition)
        if not os.path.exists(path):
            return
        # Seek to the nearest indexed record at/before start_offset, then
        # header-skip the remainder — resume cost is O(bytes after the
        # nearest index point), not O(whole log).
        index = self._index[(topic, partition)]
        offset = 0
        seek_to = 0
        if start_offset > 0 and index:
            i = min(start_offset // _INDEX_EVERY, len(index) - 1)
            offset = i * _INDEX_EVERY
            seek_to = index[i]
        with open(path, "rb") as f:
            f.seek(seek_to)
            while offset < end:
                header = f.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    return
                key, vlen = _HEADER.unpack(header)
                if offset < start_offset:
                    f.seek(vlen, os.SEEK_CUR)
                else:
                    value = f.read(vlen)
                    if len(value) < vlen:
                        return
                    yield Record(key=key, value=value, offset=offset)
                offset += 1

    def num_partitions(self, topic: str) -> int:
        return self._num_partitions_checked(topic)

    def end_offset(self, topic: str, partition: int) -> int:
        self._num_partitions_checked(topic)
        return self._counts[(topic, partition)]

    # -- lifecycle ----------------------------------------------------------

    def flush(self) -> None:
        for fh in self._files.values():
            fh.flush()
            os.fsync(fh.fileno())

    def close(self) -> None:
        for fh in self._files.values():
            fh.close()
        self._files.clear()

    def __enter__(self) -> "FileBroker":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def topics(self) -> list[str]:
        return sorted(self._partitions)
