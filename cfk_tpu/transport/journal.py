"""Factor journal: per-iteration factor checkpoints through the Transport.

The reference journals every iteration's factors through per-iteration Kafka
topics — ``user-features-i`` / ``movie-features-i``, provisioned by
``setup.sh:18-21`` and written by the calculators every half-iteration
(``apps/ALSApp.java:115-151``) — but nothing ever reads them back; a crash
restarts from scratch (``apps/BaseKafkaApp.java:36``).  This module keeps the
"topics ARE the durable checkpoint" design and adds the missing half: resume.

``JournalCheckpointManager`` exposes the same surface as the npz-directory
``CheckpointManager`` (``save``/``restore``/``latest_iteration``/
``iterations``), so every trainer accepts either, and is backed by any
``Transport`` — ``FileBroker`` for a durable on-disk journal, a
``TcpBrokerClient`` for a broker process across the network, or
``InMemoryBroker`` in tests.  Factor rows travel as ``FeatureRecord`` wire
frames (``cfk_tpu.transport.serdes``, byte-compatible with the reference's
``FeatureMessage`` serde), mod-N partitioned by entity row — the
``PureModStreamPartitioner`` rule.  A commit marker written after both
topics makes an iteration resumable: a crash mid-journal leaves topics
without a marker, and they are ignored (and rewritten) on the next save.

The npz ``CheckpointManager`` remains the fast local default; the journal is
the durable/remote option, and the live consumer of the FeatureRecord codec.
"""

from __future__ import annotations

import json

import numpy as np

from cfk_tpu.transport.checkpoint import CheckpointState

_COMMITS = "checkpoint-commits"
# Frame layout of one journaled factor row (FeatureRecord with no dependents):
# int32 id | int32 ndep=0 | int32 k | float32[k] — all big-endian.
_ROW_HEADER_BYTES = 12


def encode_feature_rows(matrix: np.ndarray, rows: np.ndarray) -> np.ndarray:
    """Vectorized FeatureRecord frames: [n, 12 + 4k] uint8, one row each.

    Byte-identical to ``serdes.encode_feature(FeatureRecord(id=row,
    dependent_ids=(), features=matrix[i]))`` — the round-trip test asserts
    this — but built with bulk numpy ops so journaling 500k-row factor
    matrices never loops in Python.
    """
    n, k = matrix.shape
    buf = np.empty((n, _ROW_HEADER_BYTES + 4 * k), np.uint8)
    buf[:, 0:4] = (
        np.ascontiguousarray(rows.astype(">i4")).view(np.uint8).reshape(n, 4)
    )
    buf[:, 4:8] = np.frombuffer(np.array(0, ">i4").tobytes(), np.uint8)
    buf[:, 8:12] = np.frombuffer(np.array(k, ">i4").tobytes(), np.uint8)
    buf[:, 12:] = (
        np.ascontiguousarray(matrix.astype(">f4")).view(np.uint8).reshape(n, 4 * k)
    )
    return buf


def decode_feature_rows(
    blob: bytes, count: int, rank: int
) -> tuple[np.ndarray, np.ndarray]:
    """(row ids [n], factors [n, rank]) from ``count`` concatenated frames."""
    frame = _ROW_HEADER_BYTES + 4 * rank
    if count * frame != len(blob):
        raise ValueError(
            f"journal partition holds {len(blob)} bytes, expected "
            f"{count} × {frame}-byte FeatureRecord frames"
        )
    arr = np.frombuffer(blob, np.uint8).reshape(count, frame)
    ids = arr[:, 0:4].copy().view(">i4").astype(np.int32).reshape(count)
    feats = (
        arr[:, _ROW_HEADER_BYTES:].copy().view(">f4").astype(np.float32)
        .reshape(count, rank)
    )
    return ids, feats


class JournalCheckpointManager:
    """Factor checkpoints as FeatureRecord frames on Transport topics.

    Topic layout per saved iteration i (names mirror ``setup.sh:18-21``):
    ``user-features-<i>`` and ``movie-features-<i>`` with ``num_partitions``
    partitions, rows mod-N partitioned by entity index; plus one commit
    marker appended to the single-partition ``checkpoint-commits`` topic
    after both are fully written.  ``keep_last`` prunes older iterations'
    topics after each successful save (the commit log itself is never
    rewritten — pruned iterations are simply no longer restorable).
    """

    def __init__(
        self,
        transport,
        *,
        num_partitions: int = 1,
        keep_last: int | None = None,
    ) -> None:
        if num_partitions < 1:
            raise ValueError(f"num_partitions must be >= 1, got {num_partitions}")
        if keep_last is not None and keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self.transport = transport
        self.num_partitions = num_partitions
        self.keep_last = keep_last

    def _ensure_commits_topic(self) -> None:
        # Created lazily on first save so restore-only usage (predict /
        # recommend serving) never mutates the target — pointing serving at
        # a wrong path errors instead of scaffolding an empty journal there.
        try:
            self.transport.create_topic(_COMMITS, 1)
        except ValueError:
            pass  # existing journal: resume against it

    @staticmethod
    def _topic(side: str, iteration: int) -> str:
        return f"{side}-features-{iteration:07d}"

    # -- write --------------------------------------------------------------

    def _write_side(self, side: str, iteration: int, matrix: np.ndarray) -> None:
        topic = self._topic(side, iteration)
        try:
            self.transport.create_topic(topic, self.num_partitions)
        except ValueError:
            # Same iteration journaled before (a crash after topics were
            # written but before the commit marker, or an over-write of a
            # resumed step): replace wholesale.
            self.transport.delete_topic(topic)
            self.transport.create_topic(topic, self.num_partitions)
        rows = np.arange(matrix.shape[0], dtype=np.int64)
        for p in range(self.num_partitions):
            sel = rows[rows % self.num_partitions == p]
            frames = encode_feature_rows(matrix[sel], sel)
            produce_rows(self.transport, topic, sel, frames, p)

    def save(
        self,
        iteration: int,
        user_factors,
        movie_factors,
        meta: dict | None = None,
    ) -> None:
        u = np.asarray(user_factors)
        m = np.asarray(movie_factors)
        stored_dtype = str(u.dtype)
        # The FeatureMessage wire format is float32
        # (serdes/FloatArray/FloatArraySerializer.java:14-25); bf16 factors
        # are upcast on the wire and re-cast at restore, like the npz store.
        u32 = u.astype(np.float32)
        m32 = m.astype(np.float32)
        self._ensure_commits_topic()
        self._write_side("user", iteration, u32)
        self._write_side("movie", iteration, m32)
        commit = {
            "iteration": iteration,
            "u_rows": int(u32.shape[0]),
            "m_rows": int(m32.shape[0]),
            "rank": int(u32.shape[1]),
            "dtype": stored_dtype,
            **(meta or {}),
        }
        self.transport.produce(
            _COMMITS, iteration, json.dumps(commit).encode(), 0
        )
        if hasattr(self.transport, "flush"):
            self.transport.flush()
        if self.keep_last is not None:
            for old in self.iterations()[: -self.keep_last]:
                self.transport.delete_topic(self._topic("user", old))
                self.transport.delete_topic(self._topic("movie", old))

    # -- read ---------------------------------------------------------------

    def _commits(self) -> dict[int, dict]:
        out: dict[int, dict] = {}
        try:
            self.transport.num_partitions(_COMMITS)
        except KeyError:
            raise FileNotFoundError(
                "no checkpoint journal here (the "
                f"{_COMMITS!r} topic does not exist) — is the path right?"
            ) from None
        for rec in self.transport.consume(_COMMITS, 0):
            commit = json.loads(rec.value.decode())
            out[int(commit["iteration"])] = commit  # later commit wins
        return out

    def _topic_exists(self, topic: str) -> bool:
        try:
            self.transport.num_partitions(topic)
        except KeyError:
            return False
        return True

    def iterations(self) -> list[int]:
        """Committed iterations whose topics still exist (not pruned)."""
        try:
            commits = self._commits()
        except FileNotFoundError:
            return []  # fresh journal: nothing saved yet
        return sorted(
            it
            for it in commits
            if self._topic_exists(self._topic("user", it))
            and self._topic_exists(self._topic("movie", it))
        )

    def latest_iteration(self) -> int | None:
        steps = self.iterations()
        return steps[-1] if steps else None

    def _read_side(self, side: str, iteration: int, rows: int, rank: int) -> np.ndarray:
        topic = self._topic(side, iteration)
        n = self.transport.num_partitions(topic)
        out = np.zeros((rows, rank), np.float32)
        seen = 0
        for p in range(n):
            blob = bytearray()
            count = 0
            for rec in self.transport.consume(topic, p):
                blob += rec.value
                count += 1
            ids, feats = decode_feature_rows(bytes(blob), count, rank)
            if ids.size and (ids.min() < 0 or ids.max() >= rows):
                raise ValueError(
                    f"journal {topic} partition {p} holds row {ids.max()} "
                    f"outside [0, {rows})"
                )
            out[ids] = feats
            seen += count
        if seen != rows:
            raise ValueError(
                f"journal {topic} holds {seen} rows, commit expects {rows}; "
                "the journal is corrupt — restore an earlier iteration"
            )
        return out

    def restore(self, iteration: int | None = None) -> CheckpointState:
        commits = self._commits()
        available = self.iterations()
        if iteration is None:
            if not available:
                raise FileNotFoundError("no committed iterations in the journal")
            iteration = available[-1]
        if iteration not in commits:
            raise FileNotFoundError(f"iteration {iteration} was never committed")
        if iteration not in available:
            raise FileNotFoundError(
                f"iteration {iteration} was pruned from the journal (keep_last)"
            )
        commit = commits[iteration]
        rank = int(commit["rank"])
        u = self._read_side("user", iteration, int(commit["u_rows"]), rank)
        m = self._read_side("movie", iteration, int(commit["m_rows"]), rank)
        want_dtype = commit.get("dtype", "float32")
        if want_dtype != "float32":
            import ml_dtypes  # ships with jax

            u = u.astype(np.dtype(getattr(ml_dtypes, want_dtype, want_dtype)))
            m = m.astype(u.dtype)
        meta = {
            k: v
            for k, v in commit.items()
            if k not in ("iteration", "u_rows", "m_rows", "rank", "dtype")
        }
        return CheckpointState(
            iteration=int(commit["iteration"]),
            user_factors=u,
            movie_factors=m,
            meta=meta,
        )


def produce_rows(
    transport, topic: str, keys: np.ndarray, frames: np.ndarray, partition: int
) -> None:
    """Append pre-encoded equal-size frames, using the transport's bulk path
    when it has one (``FileBroker.produce_frames``) and falling back to
    per-record ``produce`` otherwise."""
    fast = getattr(transport, "produce_frames", None)
    if fast is not None:
        fast(topic, keys, frames, partition)
        return
    for key, frame in zip(keys.tolist(), frames):
        transport.produce(topic, key, frame.tobytes(), partition)
