from cfk_tpu.transport.broker import InMemoryBroker, Record, Transport, mod_partition
from cfk_tpu.transport.checkpoint import CheckpointManager, CheckpointState
from cfk_tpu.transport.filelog import FileBroker
from cfk_tpu.transport.ingest import (
    RATINGS_TOPIC,
    IncompleteIngestError,
    collect_ratings,
    produce_ratings_file,
)
from cfk_tpu.transport.tcp import BrokerProcess, BrokerRequestError, TcpBrokerClient
from cfk_tpu.transport.serdes import (
    EOF_ID,
    FeatureRecord,
    IdRatingPair,
    RatingUpdate,
    decode_feature,
    decode_float_array,
    decode_id_rating,
    decode_int_list,
    decode_rating_update,
    encode_feature,
    encode_float_array,
    encode_id_rating,
    encode_int_list,
    encode_rating_update,
)

__all__ = [
    "BrokerProcess",
    "BrokerRequestError",
    "TcpBrokerClient",
    "FileBroker",
    "InMemoryBroker",
    "Record",
    "Transport",
    "mod_partition",
    "CheckpointManager",
    "CheckpointState",
    "RATINGS_TOPIC",
    "IncompleteIngestError",
    "collect_ratings",
    "produce_ratings_file",
    "EOF_ID",
    "FeatureRecord",
    "IdRatingPair",
    "RatingUpdate",
    "decode_rating_update",
    "encode_rating_update",
    "decode_feature",
    "decode_float_array",
    "decode_id_rating",
    "decode_int_list",
    "encode_feature",
    "encode_float_array",
    "encode_id_rating",
    "encode_int_list",
]
