"""Pluggable transport: the durable-log boundary of the framework.

In the reference, Kafka is the entire communication fabric (SURVEY.md §2.6).
Here the compute-path exchange is XLA collectives; the transport survives as
the *ingest + checkpoint* boundary — a partitioned, offset-addressed record
log.  ``InMemoryBroker`` is the test double (the role the reference's authors
used ``MockProcessorContext`` for, ``apps/ALSApp.java:57``); a real Kafka
client can implement the same protocol for drop-in durable ingest, using the
wire formats in ``cfk_tpu.transport.serdes``.

Partitioning is deterministic mod-N on the integer key — the reference's
``PureModPartitioner`` contract (``producers/PureModPartitioner.java:17``):
no hashing, so a record's partition is reproducible from its key alone.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Protocol


@dataclasses.dataclass(frozen=True)
class Record:
    key: int
    value: bytes
    offset: int


class Transport(Protocol):
    """Minimal partitioned-log protocol used by ingest and checkpointing."""

    def create_topic(self, name: str, num_partitions: int) -> None: ...

    def produce(self, topic: str, key: int, value: bytes,
                partition: int | None = None) -> None: ...

    def consume(self, topic: str, partition: int,
                start_offset: int = 0) -> Iterator[Record]: ...

    def num_partitions(self, topic: str) -> int: ...

    def end_offset(self, topic: str, partition: int) -> int: ...


def mod_partition(key: int, num_partitions: int) -> int:
    """Deterministic mod-N partitioning (PureModPartitioner semantics).

    Keys must be non-negative entity ids (Python and Java ``%`` diverge on
    negatives, so negative keys would partition differently across Transport
    implementations).  Control records like EOF (key −1) must be produced
    with an explicit ``partition=`` instead — which is also how the reference
    routes them (``producers/NetflixDataFormatProducer.java:64-74``).
    """
    if key < 0:
        raise ValueError(
            f"mod_partition requires a non-negative key, got {key}; produce "
            "control records with an explicit partition="
        )
    return key % num_partitions


class InMemoryBroker:
    """In-process Transport: dict of topic → list of append-only partitions."""

    def __init__(self) -> None:
        self._topics: dict[str, list[list[Record]]] = {}

    def create_topic(self, name: str, num_partitions: int) -> None:
        if num_partitions < 1:
            raise ValueError(f"num_partitions must be >= 1, got {num_partitions}")
        if name in self._topics:
            raise ValueError(f"topic {name!r} already exists")
        self._topics[name] = [[] for _ in range(num_partitions)]

    def delete_topic(self, name: str) -> None:
        self._topics.pop(name, None)

    def _partitions(self, topic: str) -> list[list[Record]]:
        try:
            return self._topics[topic]
        except KeyError:
            raise KeyError(
                f"unknown topic {topic!r}; create_topic first (the reference "
                "had the same split: setup.sh provisions topics before the app runs)"
            ) from None

    def produce(
        self, topic: str, key: int, value: bytes, partition: int | None = None
    ) -> None:
        parts = self._partitions(topic)
        if partition is None:
            partition = mod_partition(key, len(parts))
        if not 0 <= partition < len(parts):
            raise IndexError(f"partition {partition} out of range for {topic!r}")
        log = parts[partition]
        log.append(Record(key=key, value=value, offset=len(log)))

    def consume(
        self, topic: str, partition: int, start_offset: int = 0
    ) -> Iterator[Record]:
        parts = self._partitions(topic)
        yield from parts[partition][start_offset:]

    def num_partitions(self, topic: str) -> int:
        return len(self._partitions(topic))

    def end_offset(self, topic: str, partition: int) -> int:
        return len(self._partitions(topic)[partition])
