"""Per-iteration factor checkpointing + resume.

The reference's per-iteration Kafka topics (``user-features-i`` /
``movie-features-i``, provisioned by ``setup.sh:18-21``) are *incidentally* a
durable journal of every iteration's factors, but nothing ever reads them
back; any crash restarts from scratch (``streams.cleanUp()``,
``apps/BaseKafkaApp.java:36``; SURVEY.md §5).  This module makes that journal
an explicit API: factor matrices are written per iteration with an atomic
rename, and training resumes from the latest complete step.
"""

from __future__ import annotations

import atexit
import dataclasses
import json
import os
import shutil
import tempfile
import threading
import time
import warnings
import weakref
import zlib
from collections import deque

import numpy as np

_MANIFEST = "manifest.json"
_STEP_PREFIX = "step_"

# Managers with a live background writer, drained at interpreter exit so a
# process that finishes (or is SIGTERM'd into a clean shutdown) never leaves
# an enqueued checkpoint unwritten.  Weak references: a manager that is
# garbage-collected drains in __del__/wait_pending before it disappears from
# this set, and the atexit hook must not keep dead managers alive.
_LIVE_MANAGERS: "weakref.WeakSet[CheckpointManager]" = weakref.WeakSet()


def _drain_writers_at_exit() -> None:  # pragma: no cover - exit path
    for mgr in list(_LIVE_MANAGERS):
        try:
            mgr.wait_pending()
        except Exception as e:
            # Exit-time best effort: a failed background write must not turn
            # a clean shutdown into a crash loop; the warning names the loss.
            warnings.warn(f"checkpoint write pending at exit failed: {e}")


atexit.register(_drain_writers_at_exit)


class CheckpointCorruptError(ValueError):
    """An explicitly requested step failed integrity verification."""


def resume_state(
    manager: "CheckpointManager | None",
    *,
    rank: int,
    model: str,
    num_iterations: int,
    u_shape: tuple[int, int] | None = None,
    m_shape: tuple[int, int] | None = None,
    num_shards: int | None = None,
) -> "CheckpointState | None":
    """Shared resume validation for every trainer.

    Returns the latest state, or None when there is nothing to resume.
    Rejects checkpoints whose rank or model family differs from the config,
    runs already past ``num_iterations`` (silently returning over-trained
    factors as an N-iteration model would corrupt experiments), checkpoints
    whose recorded ``num_shards`` differs from this run's (shard-local
    block indices and padded row counts are shard-count-dependent, and the
    shapes can coincide by accident), and — when the expected
    ``u_shape``/``m_shape`` are given — stale checkpoints whose padded row
    counts don't match this run (different pad_multiple/num_shards), which
    would otherwise surface as an opaque shape error deep inside the jitted
    iteration.
    """
    if manager is None or manager.latest_iteration() is None:
        return None
    try:
        state = manager.restore()
    except FileNotFoundError as e:
        # Steps exist but none passed integrity verification (all torn/
        # corrupted): starting fresh beats crashing resume — the warning
        # from latest_valid_iteration() already named each bad step.
        warnings.warn(f"no intact checkpoint to resume from ({e}); "
                      "starting from scratch")
        return None
    if state.user_factors.shape[-1] != rank:
        raise ValueError(
            f"checkpoint at iteration {state.iteration} has rank "
            f"{state.user_factors.shape[-1]}, config rank={rank}; "
            "use a fresh checkpoint directory to change rank"
        )
    saved_model = state.meta.get("model", "als")
    if saved_model != model:
        raise ValueError(
            f"checkpoint was written by model family {saved_model!r}, "
            f"resuming as {model!r}; use a fresh checkpoint directory"
        )
    saved_shards = state.meta.get("num_shards")
    if (num_shards is not None and saved_shards is not None
            and int(saved_shards) != int(num_shards)):
        # The u_shape check below only catches this when the shard-count
        # padding happens to change the padded row counts; equal shapes
        # with different shard-local block layouts would train garbage.
        raise ValueError(
            f"checkpoint at iteration {state.iteration} was written by a "
            f"num_shards={int(saved_shards)} run, but this config has "
            f"num_shards={int(num_shards)}; shard-count padding and "
            "shard-local indices are not portable — use a fresh checkpoint "
            "directory (or restore() and re-shard the factors by hand)"
        )
    if state.iteration > num_iterations:
        raise ValueError(
            f"checkpoint is at iteration {state.iteration}, past the requested "
            f"num_iterations={num_iterations}; restore() an earlier step "
            "explicitly or use a fresh checkpoint directory"
        )
    if u_shape is not None:
        _check_shapes(state, u_shape, m_shape)
    return state


def checkpointed_train_loop(
    manager,
    *,
    model: str,
    rank: int,
    num_iterations: int,
    u_shape: tuple[int, int],
    m_shape: tuple[int, int],
    dtype,
    init_fn,
    step_fn,
    metrics,
    checkpoint_every: int = 1,
    num_shards: int = 1,
    preemption_guard=None,
    watchdog=None,
):
    """The single-process checkpointed training loop every trainer shares.

    Resumes from the manager's latest committed state (validated by
    ``resume_state``) or calls ``init_fn() -> (u, m)``; then steps
    ``step_fn(u, m) -> (u, m)`` from Python, journaling factors every
    ``checkpoint_every`` iterations under ``metrics`` phases.  Factoring
    this out keeps save cadence / resume validation / metrics accounting
    identical across model families by construction (ADVICE r3).

    This is the health-off special case of
    ``cfk_tpu.resilience.loop.resilient_train_loop`` (which adds sentinel
    probes, rollback and escalation); it delegates there so there is
    exactly one stepped loop.
    """
    from cfk_tpu.resilience.loop import resilient_train_loop

    return resilient_train_loop(
        manager,
        model=model,
        rank=rank,
        num_iterations=num_iterations,
        u_shape=u_shape,
        m_shape=m_shape,
        dtype=dtype,
        init_fn=init_fn,
        step_fn=step_fn,
        metrics=metrics,
        checkpoint_every=checkpoint_every,
        num_shards=num_shards,
        preemption_guard=preemption_guard,
        watchdog=watchdog,
    )


def resume_state_synced(
    manager: "CheckpointManager | None",
    *,
    rank: int,
    model: str,
    num_iterations: int,
    u_shape: tuple[int, int],
    m_shape: tuple[int, int],
    num_shards: int | None = None,
) -> "CheckpointState | None":
    """``resume_state`` with the decision broadcast from process 0.

    Under multi-process JAX, checkpoints are written by process 0 only; if
    hosts do not share a filesystem, the other processes would see no state
    (or a stale one) and start at a different iteration — their collectives
    would then no longer pair up across hosts (distributed deadlock).  This
    broadcasts process 0's (iteration, factors) so every process resumes in
    lockstep; single-process, it is exactly ``resume_state``.
    """
    import jax

    if jax.process_count() == 1:
        return resume_state(
            manager, rank=rank, model=model, num_iterations=num_iterations,
            u_shape=u_shape, m_shape=m_shape, num_shards=num_shards,
        )
    from jax.experimental import multihost_utils as mh

    # Only process 0's checkpoint is authoritative — other processes never
    # read their (possibly stale, possibly differently-shaped) local dirs;
    # they always contribute current-shape zeros to the factor broadcast.
    # Process 0 validates BEFORE any collective and broadcasts a status word,
    # so a bad checkpoint fails loudly on every process instead of leaving
    # the others hanging in a collective that process 0 never enters.
    state = None
    err: Exception | None = None
    if jax.process_index() == 0:
        try:
            state = resume_state(
                manager, rank=rank, model=model, num_iterations=num_iterations,
                u_shape=u_shape, m_shape=m_shape, num_shards=num_shards,
            )
        except Exception as e:
            err = e
        status = -2 if err is not None else (-1 if state is None else state.iteration)
    else:
        status = -1  # overwritten by the broadcast
    it = int(mh.broadcast_one_to_all(np.asarray(status, np.int64)))
    if it == -2:
        if err is not None:
            raise err
        raise RuntimeError(
            "process 0 failed to resume from its checkpoint directory "
            "(see its log for the underlying error)"
        )
    if it < 0:
        return None
    u = (
        state.user_factors.astype(np.float32)
        if state is not None
        else np.zeros(u_shape, np.float32)
    )
    m = (
        state.movie_factors.astype(np.float32)
        if state is not None
        else np.zeros(m_shape, np.float32)
    )
    return CheckpointState(
        iteration=it,
        user_factors=np.asarray(mh.broadcast_one_to_all(u)),
        movie_factors=np.asarray(mh.broadcast_one_to_all(m)),
        meta=state.meta if state is not None else {"model": model},
    )


def _check_shapes(state: "CheckpointState", u_shape, m_shape) -> None:
    got = (tuple(state.user_factors.shape), tuple(state.movie_factors.shape))
    if got != (tuple(u_shape), tuple(m_shape)):
        raise ValueError(
            f"checkpoint at iteration {state.iteration} has factor shapes "
            f"user={got[0]} movie={got[1]}, but this run needs "
            f"user={tuple(u_shape)} movie={tuple(m_shape)} (padded entity "
            "counts depend on pad_multiple/num_shards); use a fresh "
            "checkpoint directory"
        )


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic filesystems
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - fsync on dirs unsupported
        pass
    finally:
        os.close(fd)


def _host_snapshot(x) -> np.ndarray:
    """Host copy of a factor array, issued non-blocking when possible.

    jax arrays get their device→host DMA started via ``copy_to_host_async``
    before the materializing ``np.asarray`` (which must block, but now only
    for the tail of an already-running transfer); numpy inputs are copied so
    the enqueued write can never observe caller-side mutation."""
    copy_async = getattr(x, "copy_to_host_async", None)
    if copy_async is not None:
        try:
            copy_async()
        except Exception:  # pragma: no cover - non-addressable shards
            pass
    return np.array(x, copy=True)


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def should_save(done: int, every: int, total: int) -> bool:
    """Save cadence: every ``every`` completed iterations, and always at the end."""
    if every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {every}")
    return done % every == 0 or done == total


@dataclasses.dataclass(frozen=True)
class CheckpointState:
    iteration: int  # iterations fully completed
    user_factors: np.ndarray
    movie_factors: np.ndarray
    meta: dict


class CheckpointManager:
    """Directory-of-steps checkpoint store with atomic per-step commits.

    Layout: ``<dir>/step_0000007/{manifest.json,user.npy,movie.npy}``.
    A step directory appears atomically (written to a temp dir, fsync'd, then
    renamed), so a crash mid-write can never yield a half checkpoint — the
    property the reference's in-memory, changelog-disabled stores lack
    (``apps/ALSApp.java:53-83``).

    ``save_async`` hands the serialize + fsync + atomic-rename to ONE
    background writer thread so the training loop never idles behind disk;
    ``wait_pending()`` is the barrier (the resilient loop drains before any
    rollback read and at loop exit, so the crc32/torn-step verification
    contract is unchanged — readers only ever see committed steps).  When
    more than ``max_pending`` saves are queued, ``save_async`` blocks (slow
    disk must throttle the producer, not grow an unbounded host-snapshot
    queue).  A writer error is sticky: it re-raises at the next
    ``save_async``/``wait_pending`` instead of vanishing on a daemon thread.

    ``keep_last_n`` garbage-collects old steps after each successful save,
    always keeping the newest N plus any ``pin()``ned step — the resilient
    loop pins its last verified-good rollback anchor, so the step the
    recovery ladder points at can never be collected out from under it.
    """

    def __init__(
        self,
        directory: str,
        *,
        keep_last_n: int | None = None,
        async_write: bool = True,
        max_pending: int = 2,
    ) -> None:
        if keep_last_n is not None and keep_last_n < 1:
            raise ValueError(
                f"keep_last_n must be >= 1 (checkpoints retained after each "
                f"save), got {keep_last_n}; use keep_last_n=None to retain "
                "every step"
            )
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self.directory = directory
        self.keep_last_n = keep_last_n
        self.async_write = async_write
        self.max_pending = max_pending
        self._pinned: int | None = None
        self._lock = threading.Lock()
        self._queue_nonfull = threading.Condition(self._lock)
        self._queue_empty = threading.Condition(self._lock)
        self._jobs: deque = deque()
        self._inflight = 0
        self._writer_thread: threading.Thread | None = None
        self._writer_error: BaseException | None = None
        os.makedirs(directory, exist_ok=True)

    def _step_dir(self, iteration: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{iteration:07d}")

    # --- background writer -------------------------------------------------

    @property
    def pending_count(self) -> int:
        """Queued + in-flight async saves not yet committed to disk."""
        with self._lock:
            return len(self._jobs) + self._inflight

    def pin(self, iteration: int | None) -> None:
        """Protect one step from ``keep_last_n`` garbage collection — the
        resilient loop pins its last verified-good rollback anchor."""
        with self._lock:
            self._pinned = iteration

    def save_async(
        self,
        iteration: int,
        user_factors,
        movie_factors,
        meta: dict | None = None,
    ) -> None:
        """Snapshot the factors to host and enqueue the disk write.

        The snapshot happens here (device arrays are fetched via a
        non-blocking ``copy_to_host_async`` issue, then materialized) so
        the caller may mutate/donate its buffers immediately; only the
        serialize + fsync + atomic rename runs on the writer thread.
        Blocks while more than ``max_pending`` saves are queued
        (back-pressure) and re-raises any earlier writer failure.  With
        ``async_write=False`` (the A/B baseline) this is exactly ``save``.
        """
        hu, hm = _host_snapshot(user_factors), _host_snapshot(movie_factors)
        if not self.async_write:
            self.save(iteration, hu, hm, meta=meta)
            return
        _LIVE_MANAGERS.add(self)
        with self._lock:
            self._raise_writer_error_locked()
            while len(self._jobs) + self._inflight >= self.max_pending:
                self._queue_nonfull.wait()
                self._raise_writer_error_locked()
            self._jobs.append((iteration, hu, hm, dict(meta or {})))
            if self._writer_thread is None or not self._writer_thread.is_alive():
                self._writer_thread = threading.Thread(
                    target=self._writer_loop,
                    name="cfk-checkpoint-writer",
                    daemon=True,
                )
                self._writer_thread.start()

    def wait_pending(self, timeout: float | None = None) -> bool:
        """Barrier: block until every queued async save is committed.

        Returns True when drained (False on timeout) and re-raises the
        first writer error.  Safe to call with no writer running."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._jobs or self._inflight:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._queue_empty.wait(remaining)
            self._raise_writer_error_locked()
        return True

    def _raise_writer_error_locked(self) -> None:
        if self._writer_error is not None:
            err, self._writer_error = self._writer_error, None
            raise err

    def _writer_loop(self) -> None:
        while True:
            with self._lock:
                if not self._jobs:
                    self._queue_empty.notify_all()
                    # Park the thread: it dies when idle and is respawned by
                    # the next save_async (no join-at-shutdown bookkeeping).
                    self._writer_thread = None
                    return
                iteration, hu, hm, meta = self._jobs.popleft()
                self._inflight += 1
                self._queue_nonfull.notify_all()
            try:
                self.save(iteration, hu, hm, meta=meta)
            except BaseException as e:
                with self._lock:
                    if self._writer_error is None:
                        self._writer_error = e
            finally:
                with self._lock:
                    self._inflight -= 1
                    self._queue_nonfull.notify_all()
                    if not self._jobs and not self._inflight:
                        self._queue_empty.notify_all()

    def _retain(self, just_saved: int) -> None:
        """Apply the ``keep_last_n`` retention policy after a commit."""
        if self.keep_last_n is None:
            return
        steps = self.iterations()
        keep = set(steps[-self.keep_last_n:])
        keep.add(just_saved)
        with self._lock:
            if self._pinned is not None:
                keep.add(self._pinned)
        for it in steps:
            if it not in keep:
                shutil.rmtree(self._step_dir(it), ignore_errors=True)

    def save(
        self,
        iteration: int,
        user_factors,
        movie_factors,
        meta: dict | None = None,
    ) -> str:
        u = np.asarray(user_factors)
        m = np.asarray(movie_factors)
        stored_dtype = str(u.dtype)
        # npy can't round-trip ml_dtypes (bfloat16 loads back as raw void
        # bytes) — store float32 on disk and re-cast at restore.
        if u.dtype not in (np.float32, np.float64):
            u = u.astype(np.float32)
            m = m.astype(np.float32)
        tmp = tempfile.mkdtemp(dir=self.directory, prefix=".tmp_")
        try:
            np.save(os.path.join(tmp, "user.npy"), u)
            np.save(os.path.join(tmp, "movie.npy"), m)
            manifest = {
                "iteration": iteration,
                "user_shape": list(u.shape),
                "movie_shape": list(m.shape),
                "dtype": stored_dtype,
                # Content checksums of the npy payloads: the atomic rename
                # makes half-written step dirs impossible, but not silent
                # corruption *after* commit (torn page on power loss, bad
                # sector, an operator's stray truncate) — restore verifies
                # these and falls back to the previous complete step.
                "crc32": {
                    name: _crc32_file(os.path.join(tmp, name))
                    for name in ("user.npy", "movie.npy")
                },
                **(meta or {}),
            }
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            # fsync payloads + the directories on both sides of the rename:
            # the emergency (preemption) save path relies on a committed
            # step surviving an immediately-following power-off/kill, not
            # just an orderly process exit.
            for name in ("user.npy", "movie.npy"):
                _fsync_file(os.path.join(tmp, name))
            _fsync_dir(tmp)
            final = self._step_dir(iteration)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
            _fsync_dir(self.directory)
            self._retain(iteration)
            # Flight-record the commit (post-rename — the event means "this
            # step is durably on disk", the fact an incident reader needs).
            from cfk_tpu.telemetry.recorder import record_event

            record_event("checkpoint", "checkpoint_committed",
                         iteration=iteration)
            return final
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def iterations(self) -> list[int]:
        steps = []
        for name in os.listdir(self.directory):
            if not name.startswith(_STEP_PREFIX):
                continue
            full = os.path.join(self.directory, name, _MANIFEST)
            if os.path.exists(full):  # only complete (renamed) steps
                steps.append(int(name[len(_STEP_PREFIX):]))
        return sorted(steps)

    def latest_iteration(self) -> int | None:
        steps = self.iterations()
        return steps[-1] if steps else None

    def verify(self, iteration: int) -> None:
        """Integrity-check one committed step; raises
        ``CheckpointCorruptError`` on a torn/corrupted payload.

        The manifest must parse and, when it carries ``crc32`` checksums
        (every checkpoint written since they were introduced), each npy
        payload must match byte-for-byte.  Checksum-less legacy steps
        pass with only the parse check.
        """
        step = self._step_dir(iteration)
        try:
            with open(os.path.join(step, _MANIFEST)) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(
                f"checkpoint step {iteration} in {self.directory} has an "
                f"unreadable manifest ({e}); the write was torn — delete "
                f"{step} or restore an earlier step"
            ) from None
        for name, want in (manifest.get("crc32") or {}).items():
            path = os.path.join(step, name)
            try:
                got = _crc32_file(path)
            except OSError as e:
                raise CheckpointCorruptError(
                    f"checkpoint step {iteration} is missing payload "
                    f"{name!r} ({e})"
                ) from None
            if got != want:
                raise CheckpointCorruptError(
                    f"checkpoint step {iteration} payload {name!r} fails "
                    f"its manifest checksum (crc32 {got:#010x} != recorded "
                    f"{want:#010x}); the file is torn or corrupted — "
                    f"delete {step} or restore an earlier step"
                )

    def latest_valid_iteration(self) -> int | None:
        """Newest step that passes integrity verification; corrupt steps
        are skipped (with a warning) in favor of older complete ones."""
        for it in reversed(self.iterations()):
            try:
                self.verify(it)
            except CheckpointCorruptError as e:
                warnings.warn(f"skipping corrupt checkpoint: {e}")
                # Flight-record the torn step (and dump): resume silently
                # falling back past a corrupt checkpoint is exactly the
                # kind of incident that must leave a forensic trail.
                from cfk_tpu.telemetry.recorder import (
                    dump_flight,
                    record_event,
                )

                record_event("checkpoint", "corrupt_checkpoint_skipped",
                             iteration=it, error=str(e))
                dump_flight("corrupt_checkpoint")
                continue
            return it
        return None

    def manifest_meta(self, iteration: int) -> dict:
        """The caller-supplied ``meta`` of one committed step, without
        loading the factor payloads — the fleet's covering-step search
        reads many hosts' manifests and must not page in factor bytes
        to decide which step is jointly restorable.  Verifies the step
        first (same contract as ``restore``)."""
        self.verify(iteration)
        with open(os.path.join(self._step_dir(iteration), _MANIFEST)) as f:
            manifest = json.load(f)
        return {
            k: v
            for k, v in manifest.items()
            if k not in ("iteration", "user_shape", "movie_shape", "dtype",
                         "crc32")
        }

    def restore(self, iteration: int | None = None) -> CheckpointState:
        if iteration is None:
            iteration = self.latest_valid_iteration()
            if iteration is None:
                raise FileNotFoundError(
                    f"no intact checkpoints in {self.directory}"
                )
        else:
            self.verify(iteration)
        step = self._step_dir(iteration)
        with open(os.path.join(step, _MANIFEST)) as f:
            manifest = json.load(f)
        u = np.load(os.path.join(step, "user.npy"))
        m = np.load(os.path.join(step, "movie.npy"))
        want_dtype = manifest.get("dtype", "float32")
        if str(u.dtype) != want_dtype:
            import ml_dtypes  # ships with jax

            u = u.astype(np.dtype(getattr(ml_dtypes, want_dtype, want_dtype)))
            m = m.astype(u.dtype)
        meta = {
            k: v
            for k, v in manifest.items()
            if k not in ("iteration", "user_shape", "movie_shape", "dtype",
                         "crc32")
        }
        return CheckpointState(
            iteration=manifest["iteration"], user_factors=u, movie_factors=m, meta=meta
        )
