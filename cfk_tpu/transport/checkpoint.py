"""Per-iteration factor checkpointing + resume.

The reference's per-iteration Kafka topics (``user-features-i`` /
``movie-features-i``, provisioned by ``setup.sh:18-21``) are *incidentally* a
durable journal of every iteration's factors, but nothing ever reads them
back; any crash restarts from scratch (``streams.cleanUp()``,
``apps/BaseKafkaApp.java:36``; SURVEY.md §5).  This module makes that journal
an explicit API: factor matrices are written per iteration with an atomic
rename, and training resumes from the latest complete step.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
import warnings
import zlib

import numpy as np

_MANIFEST = "manifest.json"
_STEP_PREFIX = "step_"


class CheckpointCorruptError(ValueError):
    """An explicitly requested step failed integrity verification."""


def resume_state(
    manager: "CheckpointManager | None",
    *,
    rank: int,
    model: str,
    num_iterations: int,
    u_shape: tuple[int, int] | None = None,
    m_shape: tuple[int, int] | None = None,
) -> "CheckpointState | None":
    """Shared resume validation for every trainer.

    Returns the latest state, or None when there is nothing to resume.
    Rejects checkpoints whose rank or model family differs from the config,
    runs already past ``num_iterations`` (silently returning over-trained
    factors as an N-iteration model would corrupt experiments), and — when
    the expected ``u_shape``/``m_shape`` are given — stale checkpoints whose
    padded row counts don't match this run (different pad_multiple/
    num_shards), which would otherwise surface as an opaque shape error deep
    inside the jitted iteration.
    """
    if manager is None or manager.latest_iteration() is None:
        return None
    try:
        state = manager.restore()
    except FileNotFoundError as e:
        # Steps exist but none passed integrity verification (all torn/
        # corrupted): starting fresh beats crashing resume — the warning
        # from latest_valid_iteration() already named each bad step.
        warnings.warn(f"no intact checkpoint to resume from ({e}); "
                      "starting from scratch")
        return None
    if state.user_factors.shape[-1] != rank:
        raise ValueError(
            f"checkpoint at iteration {state.iteration} has rank "
            f"{state.user_factors.shape[-1]}, config rank={rank}; "
            "use a fresh checkpoint directory to change rank"
        )
    saved_model = state.meta.get("model", "als")
    if saved_model != model:
        raise ValueError(
            f"checkpoint was written by model family {saved_model!r}, "
            f"resuming as {model!r}; use a fresh checkpoint directory"
        )
    if state.iteration > num_iterations:
        raise ValueError(
            f"checkpoint is at iteration {state.iteration}, past the requested "
            f"num_iterations={num_iterations}; restore() an earlier step "
            "explicitly or use a fresh checkpoint directory"
        )
    if u_shape is not None:
        _check_shapes(state, u_shape, m_shape)
    return state


def checkpointed_train_loop(
    manager,
    *,
    model: str,
    rank: int,
    num_iterations: int,
    u_shape: tuple[int, int],
    m_shape: tuple[int, int],
    dtype,
    init_fn,
    step_fn,
    metrics,
    checkpoint_every: int = 1,
):
    """The single-process checkpointed training loop every trainer shares.

    Resumes from the manager's latest committed state (validated by
    ``resume_state``) or calls ``init_fn() -> (u, m)``; then steps
    ``step_fn(u, m) -> (u, m)`` from Python, journaling factors every
    ``checkpoint_every`` iterations under ``metrics`` phases.  Factoring
    this out keeps save cadence / resume validation / metrics accounting
    identical across model families by construction (ADVICE r3).

    This is the health-off special case of
    ``cfk_tpu.resilience.loop.resilient_train_loop`` (which adds sentinel
    probes, rollback and escalation); it delegates there so there is
    exactly one stepped loop.
    """
    from cfk_tpu.resilience.loop import resilient_train_loop

    return resilient_train_loop(
        manager,
        model=model,
        rank=rank,
        num_iterations=num_iterations,
        u_shape=u_shape,
        m_shape=m_shape,
        dtype=dtype,
        init_fn=init_fn,
        step_fn=step_fn,
        metrics=metrics,
        checkpoint_every=checkpoint_every,
    )


def resume_state_synced(
    manager: "CheckpointManager | None",
    *,
    rank: int,
    model: str,
    num_iterations: int,
    u_shape: tuple[int, int],
    m_shape: tuple[int, int],
) -> "CheckpointState | None":
    """``resume_state`` with the decision broadcast from process 0.

    Under multi-process JAX, checkpoints are written by process 0 only; if
    hosts do not share a filesystem, the other processes would see no state
    (or a stale one) and start at a different iteration — their collectives
    would then no longer pair up across hosts (distributed deadlock).  This
    broadcasts process 0's (iteration, factors) so every process resumes in
    lockstep; single-process, it is exactly ``resume_state``.
    """
    import jax

    if jax.process_count() == 1:
        return resume_state(
            manager, rank=rank, model=model, num_iterations=num_iterations,
            u_shape=u_shape, m_shape=m_shape,
        )
    from jax.experimental import multihost_utils as mh

    # Only process 0's checkpoint is authoritative — other processes never
    # read their (possibly stale, possibly differently-shaped) local dirs;
    # they always contribute current-shape zeros to the factor broadcast.
    # Process 0 validates BEFORE any collective and broadcasts a status word,
    # so a bad checkpoint fails loudly on every process instead of leaving
    # the others hanging in a collective that process 0 never enters.
    state = None
    err: Exception | None = None
    if jax.process_index() == 0:
        try:
            state = resume_state(
                manager, rank=rank, model=model, num_iterations=num_iterations,
                u_shape=u_shape, m_shape=m_shape,
            )
        except Exception as e:
            err = e
        status = -2 if err is not None else (-1 if state is None else state.iteration)
    else:
        status = -1  # overwritten by the broadcast
    it = int(mh.broadcast_one_to_all(np.asarray(status, np.int64)))
    if it == -2:
        if err is not None:
            raise err
        raise RuntimeError(
            "process 0 failed to resume from its checkpoint directory "
            "(see its log for the underlying error)"
        )
    if it < 0:
        return None
    u = (
        state.user_factors.astype(np.float32)
        if state is not None
        else np.zeros(u_shape, np.float32)
    )
    m = (
        state.movie_factors.astype(np.float32)
        if state is not None
        else np.zeros(m_shape, np.float32)
    )
    return CheckpointState(
        iteration=it,
        user_factors=np.asarray(mh.broadcast_one_to_all(u)),
        movie_factors=np.asarray(mh.broadcast_one_to_all(m)),
        meta=state.meta if state is not None else {"model": model},
    )


def _check_shapes(state: "CheckpointState", u_shape, m_shape) -> None:
    got = (tuple(state.user_factors.shape), tuple(state.movie_factors.shape))
    if got != (tuple(u_shape), tuple(m_shape)):
        raise ValueError(
            f"checkpoint at iteration {state.iteration} has factor shapes "
            f"user={got[0]} movie={got[1]}, but this run needs "
            f"user={tuple(u_shape)} movie={tuple(m_shape)} (padded entity "
            "counts depend on pad_multiple/num_shards); use a fresh "
            "checkpoint directory"
        )


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def should_save(done: int, every: int, total: int) -> bool:
    """Save cadence: every ``every`` completed iterations, and always at the end."""
    if every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {every}")
    return done % every == 0 or done == total


@dataclasses.dataclass(frozen=True)
class CheckpointState:
    iteration: int  # iterations fully completed
    user_factors: np.ndarray
    movie_factors: np.ndarray
    meta: dict


class CheckpointManager:
    """Directory-of-steps checkpoint store with atomic per-step commits.

    Layout: ``<dir>/step_0000007/{manifest.json,user.npy,movie.npy}``.
    A step directory appears atomically (written to a temp dir, then renamed),
    so a crash mid-write can never yield a half checkpoint — the property the
    reference's in-memory, changelog-disabled stores lack (``apps/ALSApp.java:53-83``).
    """

    def __init__(self, directory: str) -> None:
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _step_dir(self, iteration: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{iteration:07d}")

    def save(
        self,
        iteration: int,
        user_factors,
        movie_factors,
        meta: dict | None = None,
    ) -> str:
        u = np.asarray(user_factors)
        m = np.asarray(movie_factors)
        stored_dtype = str(u.dtype)
        # npy can't round-trip ml_dtypes (bfloat16 loads back as raw void
        # bytes) — store float32 on disk and re-cast at restore.
        if u.dtype not in (np.float32, np.float64):
            u = u.astype(np.float32)
            m = m.astype(np.float32)
        tmp = tempfile.mkdtemp(dir=self.directory, prefix=".tmp_")
        try:
            np.save(os.path.join(tmp, "user.npy"), u)
            np.save(os.path.join(tmp, "movie.npy"), m)
            manifest = {
                "iteration": iteration,
                "user_shape": list(u.shape),
                "movie_shape": list(m.shape),
                "dtype": stored_dtype,
                # Content checksums of the npy payloads: the atomic rename
                # makes half-written step dirs impossible, but not silent
                # corruption *after* commit (torn page on power loss, bad
                # sector, an operator's stray truncate) — restore verifies
                # these and falls back to the previous complete step.
                "crc32": {
                    name: _crc32_file(os.path.join(tmp, name))
                    for name in ("user.npy", "movie.npy")
                },
                **(meta or {}),
            }
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f)
            final = self._step_dir(iteration)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
            return final
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def iterations(self) -> list[int]:
        steps = []
        for name in os.listdir(self.directory):
            if not name.startswith(_STEP_PREFIX):
                continue
            full = os.path.join(self.directory, name, _MANIFEST)
            if os.path.exists(full):  # only complete (renamed) steps
                steps.append(int(name[len(_STEP_PREFIX):]))
        return sorted(steps)

    def latest_iteration(self) -> int | None:
        steps = self.iterations()
        return steps[-1] if steps else None

    def verify(self, iteration: int) -> None:
        """Integrity-check one committed step; raises
        ``CheckpointCorruptError`` on a torn/corrupted payload.

        The manifest must parse and, when it carries ``crc32`` checksums
        (every checkpoint written since they were introduced), each npy
        payload must match byte-for-byte.  Checksum-less legacy steps
        pass with only the parse check.
        """
        step = self._step_dir(iteration)
        try:
            with open(os.path.join(step, _MANIFEST)) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(
                f"checkpoint step {iteration} in {self.directory} has an "
                f"unreadable manifest ({e}); the write was torn — delete "
                f"{step} or restore an earlier step"
            ) from None
        for name, want in (manifest.get("crc32") or {}).items():
            path = os.path.join(step, name)
            try:
                got = _crc32_file(path)
            except OSError as e:
                raise CheckpointCorruptError(
                    f"checkpoint step {iteration} is missing payload "
                    f"{name!r} ({e})"
                ) from None
            if got != want:
                raise CheckpointCorruptError(
                    f"checkpoint step {iteration} payload {name!r} fails "
                    f"its manifest checksum (crc32 {got:#010x} != recorded "
                    f"{want:#010x}); the file is torn or corrupted — "
                    f"delete {step} or restore an earlier step"
                )

    def latest_valid_iteration(self) -> int | None:
        """Newest step that passes integrity verification; corrupt steps
        are skipped (with a warning) in favor of older complete ones."""
        for it in reversed(self.iterations()):
            try:
                self.verify(it)
            except CheckpointCorruptError as e:
                warnings.warn(f"skipping corrupt checkpoint: {e}")
                continue
            return it
        return None

    def restore(self, iteration: int | None = None) -> CheckpointState:
        if iteration is None:
            iteration = self.latest_valid_iteration()
            if iteration is None:
                raise FileNotFoundError(
                    f"no intact checkpoints in {self.directory}"
                )
        else:
            self.verify(iteration)
        step = self._step_dir(iteration)
        with open(os.path.join(step, _MANIFEST)) as f:
            manifest = json.load(f)
        u = np.load(os.path.join(step, "user.npy"))
        m = np.load(os.path.join(step, "movie.npy"))
        want_dtype = manifest.get("dtype", "float32")
        if str(u.dtype) != want_dtype:
            import ml_dtypes  # ships with jax

            u = u.astype(np.dtype(getattr(ml_dtypes, want_dtype, want_dtype)))
            m = m.astype(u.dtype)
        meta = {
            k: v
            for k, v in manifest.items()
            if k not in ("iteration", "user_shape", "movie_shape", "dtype",
                         "crc32")
        }
        return CheckpointState(
            iteration=manifest["iteration"], user_factors=u, movie_factors=m, meta=meta
        )
