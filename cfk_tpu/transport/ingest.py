"""Streaming ingest over a Transport, with the EOF-barrier protocol.

Mirrors the reference's ingest contract (re-designed, not translated):

- ``produce_ratings_file`` is the analog of ``NetflixDataFormatProducer``
  (``producers/NetflixDataFormatProducer.java:38-75``): stream the Netflix
  file into a ratings topic keyed by movieId (mod-N partitioned), then send
  one EOF control record to *every* partition explicitly (``:64-74``).
- ``collect_ratings`` is the batch analog of the two *Ratings2Blocks
  processors plus their EOF barrier: a partition's data is complete if and
  only if its log contains the EOF record.  The reference learned this the
  hard way — its first version started ALS before all partitions were done
  (the race recounted in its README) and hangs forever when a message goes
  missing (SURVEY.md §5 failure modes).  Here incompleteness is a loud
  ``IncompleteIngestError`` naming the missing partitions, not a hang.
"""

from __future__ import annotations

import numpy as np

from cfk_tpu.data.blocks import RatingsCOO
from cfk_tpu.transport.broker import Transport, mod_partition
from cfk_tpu.transport.serdes import (
    EOF_ID,
    IdRatingPair,
    decode_id_rating,
    encode_id_rating,
)

RATINGS_TOPIC = "movieIds-with-ratings"


class IncompleteIngestError(RuntimeError):
    """A partition's log has no EOF record — ingest did not finish."""


def produce_ratings_file(
    transport: Transport,
    path: str,
    *,
    topic: str = RATINGS_TOPIC,
    send_eof: bool = True,
    drop_eof_for: set[int] | None = None,
) -> int:
    """Stream a Netflix-format file into ``topic``, keyed by movieId.

    Returns the number of rating records produced.  ``send_eof=False`` skips
    the EOF fan-out so further files can be appended to the topic; the LAST
    produce must send EOF or ``collect_ratings`` refuses the topic (records
    after an EOF also fail the barrier — EOF means *end*, exactly as in the
    reference's protocol).  ``drop_eof_for`` is a fault-injection hook:
    partitions listed there do NOT receive their EOF record (simulating the
    reference's lost-message failure mode).
    """
    n = transport.num_partitions(topic)
    produced = 0
    current_movie = -1
    with open(path, "r") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                if line.endswith(":"):
                    current_movie = int(line[:-1])
                    continue
                user_s, rating_s, _ = line.split(",", 2)
                user_id, rating = int(user_s), int(rating_s)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: malformed line {line!r}") from e
            if current_movie < 0:
                raise ValueError(
                    f"{path}:{lineno}: rating row before any 'movieId:' header"
                )
            # Value = (userId, rating) keyed by movieId — the reference's
            # record shape on movieIds-with-ratings.
            transport.produce(
                topic,
                key=current_movie,
                value=encode_id_rating(IdRatingPair(id=user_id, rating=rating)),
            )
            produced += 1
    if not send_eof:
        return produced
    drop = drop_eof_for or set()
    for p in range(n):
        if p in drop:
            continue
        transport.produce(
            topic,
            key=EOF_ID,
            value=encode_id_rating(IdRatingPair(id=EOF_ID, rating=p)),
            partition=p,
        )
    return produced


def collect_ratings(
    transport: Transport, *, topic: str = RATINGS_TOPIC
) -> RatingsCOO:
    """Drain all partitions into a RatingsCOO, enforcing the EOF barrier.

    Also validates partition placement: every rating record must sit on
    ``movieId mod N`` (PureModPartitioner invariant), so a mis-partitioned
    producer is caught at ingest rather than as silently wrong blocks.
    """
    n = transport.num_partitions(topic)
    movie_ids: list[int] = []
    user_ids: list[int] = []
    ratings: list[int] = []
    missing_eof = []
    for p in range(n):
        saw_eof = False
        for record in transport.consume(topic, p):
            msg = decode_id_rating(record.value)
            if record.key == EOF_ID or msg.is_eof:
                saw_eof = True
                continue
            if saw_eof:
                raise IncompleteIngestError(
                    f"partition {p}: record at offset {record.offset} arrived "
                    "after EOF — producer restarted without topic reset?"
                )
            if mod_partition(record.key, n) != p:
                raise IncompleteIngestError(
                    f"partition {p}: movieId {record.key} belongs on partition "
                    f"{mod_partition(record.key, n)} (mod-{n} invariant broken)"
                )
            movie_ids.append(record.key)
            user_ids.append(msg.id)
            ratings.append(msg.rating)
        if not saw_eof:
            missing_eof.append(p)
    if missing_eof:
        raise IncompleteIngestError(
            f"no EOF record on partition(s) {missing_eof}; ingest incomplete "
            "(the reference hangs forever in this state — we fail loudly)"
        )
    return RatingsCOO(
        movie_raw=np.asarray(movie_ids, dtype=np.int64),
        user_raw=np.asarray(user_ids, dtype=np.int64),
        rating=np.asarray(ratings, dtype=np.float32),
    )
