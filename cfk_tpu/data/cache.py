"""On-disk dataset cache: skip the host-side block build on repeat runs.

At full-Netflix scale parsing + indexing + block building costs minutes of
host time per process start while the result is fully deterministic for a
given (data, layout, shards, chunking) tuple.  ``save_dataset`` serializes a
built ``Dataset`` — every block layout, both sides, id maps, and the dense
COO — into one uncompressed ``.npz`` (arrays) plus a JSON skeleton
(dataclass structure and scalars); ``load_dataset`` rebuilds it with zero
recomputation.  The reference has no analog (it re-ingests through Kafka on
every run); this is the standard at-scale workflow for repeated training.

Format: the object tree is walked generically — any frozen dataclass whose
fields are ndarrays / scalars / None / tuples of dataclasses round-trips —
so new block layouts serialize without touching this module (they only need
registering in ``_CLASSES``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
import uuid

import numpy as np

from cfk_tpu.data.blocks import (
    Bucket,
    BucketedBlocks,
    Dataset,
    IdMap,
    PaddedBlocks,
    RatingsCOO,
    SegmentBlocks,
    TiledBlocks,
)

# 1: arrays always in "arrays.npz". 2: uniquely-named arrays file recorded in
# meta.json "arrays" (meta is the atomic commit point pairing the two).
# 3: tiled-layout padding entries index the appended zero row of the fixed
#    table (neighbor = slice height) instead of row 0 — pre-3 TILED caches
#    would silently compute garbage under the unit-weight fast path, so
#    those specifically are refused (other layouts are unchanged and stay
#    readable).
_FORMAT_VERSION = 3
_READABLE_VERSIONS = (1, 2, 3)

_CLASSES = {
    cls.__name__: cls
    for cls in (
        Bucket,
        BucketedBlocks,
        Dataset,
        IdMap,
        PaddedBlocks,
        RatingsCOO,
        SegmentBlocks,
        TiledBlocks,
    )
}


def _flatten(obj, prefix: str, arrays: dict):
    if isinstance(obj, np.ndarray):
        arrays[prefix] = obj
        return {"__array__": prefix}
    if isinstance(obj, (bool, int, float, str)) or obj is None:
        return obj
    if isinstance(obj, tuple):
        return {
            "__tuple__": [
                _flatten(x, f"{prefix}.{i}", arrays) for i, x in enumerate(obj)
            ]
        }
    if dataclasses.is_dataclass(obj):
        name = type(obj).__name__
        if name not in _CLASSES:
            raise TypeError(f"unregistered dataclass in dataset tree: {name}")
        return {
            "__class__": name,
            "fields": {
                f.name: _flatten(getattr(obj, f.name), f"{prefix}.{f.name}", arrays)
                for f in dataclasses.fields(obj)
            },
        }
    raise TypeError(f"cannot serialize {type(obj).__name__} at {prefix!r}")


def _unflatten(spec, arrays):
    if isinstance(spec, dict):
        if "__array__" in spec:
            return arrays[spec["__array__"]]
        if "__tuple__" in spec:
            return tuple(_unflatten(x, arrays) for x in spec["__tuple__"])
        cls = _CLASSES[spec["__class__"]]
        return cls(
            **{k: _unflatten(v, arrays) for k, v in spec["fields"].items()}
        )
    return spec


# A concurrent save may still be mid-write to its own uniquely-named arrays
# file when another save's cleanup pass runs; only unlink files at least this
# stale so cleanup never races an in-flight writer.
_CLEANUP_AGE_S = 600.0


def save_dataset(dataset: Dataset, path: str, build_key: dict | None = None) -> None:
    """Write ``dataset`` under directory ``path`` (created if missing).

    Crash- and concurrency-safe: arrays go to a uniquely-named file first and
    ``meta.json`` — the single commit point, written by atomic rename — is
    what pairs a skeleton with its arrays file.  A crash mid-save leaves the
    previous cache fully intact; two concurrent saves each publish a
    self-consistent (meta, arrays) pair and the last rename wins.

    ``build_key`` (any JSON-serializable dict — e.g. data path + layout
    flags) is stored verbatim; ``load_dataset`` can require it to match so a
    cache built under different flags is never silently reused.
    """
    os.makedirs(path, exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    skeleton = _flatten(dataset, "ds", arrays)
    arrays_name = f"arrays-{uuid.uuid4().hex}.npz"
    tmp = os.path.join(path, f".{arrays_name}.tmp")
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, os.path.join(path, arrays_name))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    meta = {
        "format_version": _FORMAT_VERSION,
        "skeleton": skeleton,
        "arrays": arrays_name,
        "build_key": build_key,
    }
    fd, tmp = tempfile.mkstemp(dir=path, prefix=".meta.json.")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(path, "meta.json"))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _cleanup_stale(path, keep=arrays_name)


def _cleanup_stale(path: str, keep: str) -> None:
    """Remove files orphaned by earlier saves: superseded arrays files and
    temp files left by hard-crashed writers (SIGKILL during np.savez never
    runs the except-cleanup — at full-Netflix scale each such .tmp is
    multi-GB).  Never touches the live pair or anything recent enough to be
    a concurrent save in flight."""
    now = time.time()
    # Protect whatever arrays file the current meta.json references, not
    # just ``keep``: a loader that stalled past the age guard would
    # otherwise unlink the pair a concurrent rebuild published meanwhile.
    live = {keep, "meta.json"}
    try:
        with open(os.path.join(path, "meta.json")) as f:
            live.add(json.load(f).get("arrays", "arrays.npz"))
    except (OSError, ValueError):
        pass
    for name in os.listdir(path):
        if name in live:
            continue
        orphan = (
            (name.startswith("arrays") or name.startswith(".arrays"))
            and (name.endswith(".npz") or name.endswith(".npz.tmp"))
        ) or name.startswith(".meta.json.")
        if not orphan:
            continue
        full = os.path.join(path, name)
        try:
            if now - os.path.getmtime(full) > _CLEANUP_AGE_S:
                os.unlink(full)
        except OSError:
            pass


def read_build_key(path: str) -> dict | None:
    """The build key stored with the cache at ``path`` (None if the cache
    predates build keys or none was given).  Lets callers make their own
    freshness decision when parts of the key cannot be recomputed — e.g. a
    broker-offset fingerprint while the broker is unreachable."""
    with open(os.path.join(path, "meta.json")) as f:
        return json.load(f).get("build_key")


def load_dataset(path: str, expect_build_key: dict | None = None) -> Dataset:
    """Load a dataset previously written by ``save_dataset``.

    With ``expect_build_key``, the stored build key must equal it exactly —
    a cache written from different data or layout flags (or one predating
    build keys) raises instead of silently training on the wrong blocks.
    """
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("format_version") not in _READABLE_VERSIONS:
        raise ValueError(
            f"dataset cache at {path!r} has format_version "
            f"{meta.get('format_version')!r}; this build reads "
            f"{_READABLE_VERSIONS}"
        )
    if expect_build_key is not None and meta.get("build_key") != expect_build_key:
        raise ValueError(
            f"dataset cache at {path!r} was built with "
            f"{meta.get('build_key')!r}, which does not match the requested "
            f"{expect_build_key!r}; rebuild (or delete the cache dir)"
        )
    if meta.get("format_version") < 3 and "TiledBlocks" in json.dumps(
        meta["skeleton"]
    ):
        raise ValueError(
            f"dataset cache at {path!r} holds format-"
            f"{meta.get('format_version')} tiled blocks, whose padding "
            "entries index row 0 instead of the appended zero row; this "
            "build would compute garbage from them — delete the cache dir "
            "and rebuild"
        )
    arrays_file = meta.get("arrays", "arrays.npz")
    with np.load(os.path.join(path, arrays_file)) as z:
        arrays = {k: z[k] for k in z.files}
    ds = _unflatten(meta["skeleton"], arrays)
    # Sweep superseded files here too: the common steady state is hit-only
    # (save never runs again), which would otherwise retain a multi-GB
    # arrays file orphaned by the last rebuild forever.
    _cleanup_stale(path, keep=arrays_file)
    return ds


def cached_scale_dataset(
    *,
    users: int,
    movies: int,
    nnz: int,
    seed: int = 0,
    layout: str = "tiled",
    chunk_elems: int = 1 << 19,
    tile_rows: int = 128,
    slice_rows: int | None = None,
    accum_chunk_elems: int | None = None,
    dense_stream: bool = False,
    cache_root: str | None = None,
    log=print,
) -> Dataset:
    """Build-or-load a synthetic Netflix-shaped dataset, disk-cached.

    The shared steady-state measurement path of ``scripts/perf_lab.py``
    and ``bench.py``'s headline rows: at full-corpus shapes the host-side
    block build costs minutes while being fully deterministic for the
    key below, so both tools key the same cache (tag format unchanged
    from perf_lab round 2 — existing caches keep hitting).
    """
    import time

    from cfk_tpu.data.blocks import TILED_SLICE_ROWS_DEFAULT
    from cfk_tpu.data.synthetic import synthetic_netflix_coo

    if slice_rows is None:
        slice_rows = TILED_SLICE_ROWS_DEFAULT
    root = cache_root or os.environ.get(
        "CFK_PERF_CACHE", "/tmp/cfk_perf_cache"
    )
    key = {
        "users": users, "movies": movies, "nnz": nnz,
        "seed": seed, "layout": layout,
        "chunk_elems": chunk_elems,
    }
    if layout == "tiled":
        key["tile_rows"] = tile_rows
        if slice_rows != TILED_SLICE_ROWS_DEFAULT:
            key["slice_rows"] = slice_rows
        if accum_chunk_elems is not None:
            key["accum_chunk_elems"] = accum_chunk_elems
        if dense_stream:
            key["dense"] = 1
    tag = "_".join(f"{k}{v}" for k, v in key.items())
    path = os.path.join(root, tag)
    if os.path.exists(path):
        t0 = time.time()
        try:
            ds = Dataset.load(path, expect_build_key=key)
        except (FileNotFoundError, ValueError, TypeError):
            pass  # torn/mismatched/stale-format cache: rebuild below
        else:
            log(f"# dataset cache hit ({time.time()-t0:.1f}s load)",
                flush=True)
            return ds
    t0 = time.time()
    coo = synthetic_netflix_coo(users, movies, nnz, seed=seed)
    if layout == "tiled":
        from cfk_tpu.data.blocks import (
            RatingsCOO,
            build_tiled_blocks,
            index_entities,
        )

        movie_map, m_dense = index_entities(coo.movie_raw)
        user_map, u_dense = index_entities(coo.user_raw)
        mb = build_tiled_blocks(
            m_dense, u_dense, coo.rating,
            movie_map.num_entities, user_map.num_entities,
            tile_rows=tile_rows,
            chunk_elems=(chunk_elems if accum_chunk_elems is None
                         else accum_chunk_elems),
            slice_rows=slice_rows,
        )
        ub = build_tiled_blocks(
            u_dense, m_dense, coo.rating,
            user_map.num_entities, movie_map.num_entities,
            tile_rows=tile_rows, chunk_elems=chunk_elems,
            slice_rows=slice_rows, dense_stream=dense_stream,
        )
        ds = Dataset(
            movie_map=movie_map, user_map=user_map,
            movie_blocks=mb, user_blocks=ub,
            coo_dense=RatingsCOO(
                movie_raw=m_dense.astype(np.int64),
                user_raw=u_dense.astype(np.int64),
                rating=coo.rating.astype(np.float32),
            ),
        )
    else:
        ds = Dataset.from_coo(coo, layout=layout, chunk_elems=chunk_elems)
    log(f"# dataset built in {time.time()-t0:.1f}s", flush=True)
    os.makedirs(root, exist_ok=True)
    ds.save(path, build_key=key)
    return ds
