"""On-disk dataset cache: skip the host-side block build on repeat runs.

At full-Netflix scale parsing + indexing + block building costs minutes of
host time per process start while the result is fully deterministic for a
given (data, layout, shards, chunking) tuple.  ``save_dataset`` serializes a
built ``Dataset`` — every block layout, both sides, id maps, and the dense
COO — into one uncompressed ``.npz`` (arrays) plus a JSON skeleton
(dataclass structure and scalars); ``load_dataset`` rebuilds it with zero
recomputation.  The reference has no analog (it re-ingests through Kafka on
every run); this is the standard at-scale workflow for repeated training.

Format: the object tree is walked generically — any frozen dataclass whose
fields are ndarrays / scalars / None / tuples of dataclasses round-trips —
so new block layouts serialize without touching this module (they only need
registering in ``_CLASSES``).
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from cfk_tpu.data.blocks import (
    Bucket,
    BucketedBlocks,
    Dataset,
    IdMap,
    PaddedBlocks,
    RatingsCOO,
    SegmentBlocks,
)

_FORMAT_VERSION = 1

_CLASSES = {
    cls.__name__: cls
    for cls in (
        Bucket,
        BucketedBlocks,
        Dataset,
        IdMap,
        PaddedBlocks,
        RatingsCOO,
        SegmentBlocks,
    )
}


def _flatten(obj, prefix: str, arrays: dict):
    if isinstance(obj, np.ndarray):
        arrays[prefix] = obj
        return {"__array__": prefix}
    if isinstance(obj, (bool, int, float, str)) or obj is None:
        return obj
    if isinstance(obj, tuple):
        return {
            "__tuple__": [
                _flatten(x, f"{prefix}.{i}", arrays) for i, x in enumerate(obj)
            ]
        }
    if dataclasses.is_dataclass(obj):
        name = type(obj).__name__
        if name not in _CLASSES:
            raise TypeError(f"unregistered dataclass in dataset tree: {name}")
        return {
            "__class__": name,
            "fields": {
                f.name: _flatten(getattr(obj, f.name), f"{prefix}.{f.name}", arrays)
                for f in dataclasses.fields(obj)
            },
        }
    raise TypeError(f"cannot serialize {type(obj).__name__} at {prefix!r}")


def _unflatten(spec, arrays):
    if isinstance(spec, dict):
        if "__array__" in spec:
            return arrays[spec["__array__"]]
        if "__tuple__" in spec:
            return tuple(_unflatten(x, arrays) for x in spec["__tuple__"])
        cls = _CLASSES[spec["__class__"]]
        return cls(
            **{k: _unflatten(v, arrays) for k, v in spec["fields"].items()}
        )
    return spec


def save_dataset(dataset: Dataset, path: str) -> None:
    """Write ``dataset`` under directory ``path`` (created if missing)."""
    os.makedirs(path, exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    skeleton = _flatten(dataset, "ds", arrays)
    # Write-then-rename so a crashed save never looks loadable.
    tmp = os.path.join(path, ".arrays.npz.tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, os.path.join(path, "arrays.npz"))
    meta = {"format_version": _FORMAT_VERSION, "skeleton": skeleton}
    tmp = os.path.join(path, ".meta.json.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(path, "meta.json"))


def load_dataset(path: str) -> Dataset:
    """Load a dataset previously written by ``save_dataset``."""
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("format_version") != _FORMAT_VERSION:
        raise ValueError(
            f"dataset cache at {path!r} has format_version "
            f"{meta.get('format_version')!r}; this build reads {_FORMAT_VERSION}"
        )
    with np.load(os.path.join(path, "arrays.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    return _unflatten(meta["skeleton"], arrays)
