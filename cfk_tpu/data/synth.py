"""Counter-based synthetic power-law ratings — the billion-interaction lab's
data source (ISSUE 11 / ROADMAP "New directions" item 3).

``data/synthetic.py`` materializes the whole COO through a sequential RNG,
which has two problems at the ALX regime (~1B ratings / 10M users,
arXiv 2112.02194): the full arrays are ~16 GB of host RAM before a single
block is built, and the draw is stateful — generating the stream in chunks
(or per shard) changes every value after the first boundary.  This module
makes the stream a PURE FUNCTION of ``(seed, index)``:

- every rating entry ``i`` is derived from a splitmix64-style counter hash
  (one stream per field: user draw, movie draw, rating), so entry ``i`` has
  the same bits no matter which chunk, process, or shard materializes it —
  "deterministic by construction", pinned by ``crc32()`` in
  ``tests/test_synth.py``;
- popularity is Zipf on both axes (the property that stresses the block
  layouts), realized by inverse-CDF lookup into an O(num_entities) float64
  cumulative table — the only materialized state, ~160 MB at 10M users;
  nothing dense in the interaction space ever exists;
- entity ids are scattered through the id space by a seeded permutation
  (like ``synthetic.py``) so contiguous-range sharding stays load-balanced.

``chunk(lo, hi)`` yields any index range independently; ``coo()`` is the
small-shape convenience that materializes one ``RatingsCOO`` (tests, the
offload parity suite); ``iter_chunks`` / ``crc32`` stream without ever
holding more than one chunk.
"""

from __future__ import annotations

import dataclasses
import zlib

import numpy as np

from cfk_tpu.data.blocks import RatingsCOO

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)
# Field streams: the per-entry draws must be independent across fields, so
# each field hashes a distinct stream constant into the counter.
_STREAM_USER = np.uint64(0x243F6A8885A308D3)
_STREAM_MOVIE = np.uint64(0x13198A2E03707344)
_STREAM_RATING = np.uint64(0xA4093822299F31D0)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over a uint64 array (vectorized, stateless)."""
    z = x.astype(np.uint64, copy=True)
    z ^= z >> np.uint64(30)
    z *= _MIX1
    z ^= z >> np.uint64(27)
    z *= _MIX2
    z ^= z >> np.uint64(31)
    return z


def _counter_uniform(seed: int, stream: np.uint64, lo: int, hi: int
                     ) -> np.ndarray:
    """U[0, 1) float64 for indices [lo, hi): ``mix(seed·φ ^ stream + i·φ)``
    — pure in (seed, stream, i), so any chunking of the index range
    produces identical values."""
    idx = np.arange(lo, hi, dtype=np.uint64)
    # 0-d array keeps the deliberate mod-2^64 wrap silent (numpy warns on
    # overflowing SCALAR uint ops only).
    base = (np.asarray(seed & 0xFFFFFFFFFFFFFFFF, np.uint64) * _GOLDEN
            ) ^ stream
    z = _mix64(base + (idx + np.uint64(1)) * _GOLDEN)
    # 53-bit mantissa path: exactly representable, bit-stable.
    return (z >> np.uint64(11)).astype(np.float64) * (2.0 ** -53)


def zipf_cdf(n: int, skew: float) -> np.ndarray:
    """Cumulative Zipf(skew) over ranks 1..n (float64; the inverse-CDF
    lookup table — O(n) memory, the module's only materialized state)."""
    p = (1.0 / np.arange(1, n + 1, dtype=np.float64)) ** skew
    cdf = np.cumsum(p / p.sum())
    cdf[-1] = 1.0  # guard searchsorted against cumsum rounding
    return cdf


@dataclasses.dataclass(frozen=True)
class SynthSpec:
    """Shape + seed of one synthetic power-law corpus.  Two specs with the
    same fields generate bit-identical streams on any machine."""

    num_users: int
    num_movies: int
    nnz: int
    seed: int = 0
    user_skew: float = 0.7
    movie_skew: float = 0.9

    def __post_init__(self) -> None:
        for f in ("num_users", "num_movies", "nnz"):
            if getattr(self, f) < 1:
                raise ValueError(f"{f} must be >= 1, got {getattr(self, f)}")

    def shard_range(self, shard: int, num_shards: int) -> tuple[int, int]:
        """Contiguous index range of ``shard``'s entries (balanced split;
        the union over shards tiles [0, nnz) exactly — both bounds clamp,
        so a ceil-split overshooting nnz by more than one shard leaves
        trailing shards EMPTY instead of inverted)."""
        if not 0 <= shard < num_shards:
            raise ValueError(f"shard {shard} not in [0, {num_shards})")
        per = -(-self.nnz // num_shards)
        return min(shard * per, self.nnz), min((shard + 1) * per, self.nnz)


class PowerLawSynth:
    """Chunk-addressable generator for a ``SynthSpec`` (see module doc)."""

    def __init__(self, spec: SynthSpec) -> None:
        self.spec = spec
        # The permutations and CDF tables come from ONE seeded generator in
        # a fixed draw order; per-entry values never touch it (they are
        # counter-hashed), so chunk boundaries cannot perturb anything.
        rng = np.random.default_rng(spec.seed)
        self._m_ids = rng.permutation(spec.num_movies).astype(np.int64) + 1
        self._u_ids = rng.permutation(spec.num_users).astype(np.int64) + 1
        self._m_cdf = zipf_cdf(spec.num_movies, spec.movie_skew)
        self._u_cdf = zipf_cdf(spec.num_users, spec.user_skew)

    def chunk(self, lo: int, hi: int
              ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(user_raw, movie_raw, rating) for entries [lo, hi) — bit-equal
        to the same slice of any other chunking."""
        s = self.spec
        if not 0 <= lo <= hi <= s.nnz:
            raise ValueError(f"chunk [{lo}, {hi}) outside [0, {s.nnz})")
        uu = _counter_uniform(s.seed, _STREAM_USER, lo, hi)
        um = _counter_uniform(s.seed, _STREAM_MOVIE, lo, hi)
        ur = _counter_uniform(s.seed, _STREAM_RATING, lo, hi)
        u_idx = np.searchsorted(self._u_cdf, uu, side="right")
        m_idx = np.searchsorted(self._m_cdf, um, side="right")
        # searchsorted can return n when u lands exactly on the guarded 1.0
        np.clip(u_idx, 0, s.num_users - 1, out=u_idx)
        np.clip(m_idx, 0, s.num_movies - 1, out=m_idx)
        rating = (1.0 + np.floor(ur * 5.0)).astype(np.float32)
        return self._u_ids[u_idx], self._m_ids[m_idx], rating

    def iter_chunks(self, chunk_elems: int = 1 << 22):
        """Yield ``(lo, hi, user_raw, movie_raw, rating)`` over the whole
        stream without ever materializing more than one chunk."""
        if chunk_elems < 1:
            raise ValueError(f"chunk_elems must be >= 1, got {chunk_elems}")
        for lo in range(0, self.spec.nnz, chunk_elems):
            hi = min(lo + chunk_elems, self.spec.nnz)
            u, m, r = self.chunk(lo, hi)
            yield lo, hi, u, m, r

    def coo(self, lo: int = 0, hi: int | None = None) -> RatingsCOO:
        """Materialize entries [lo, hi) as a ``RatingsCOO`` (small shapes:
        tests, block builds, the offload parity suite)."""
        u, m, r = self.chunk(lo, self.spec.nnz if hi is None else hi)
        return RatingsCOO(movie_raw=m, user_raw=u, rating=r)

    def crc32(self, chunk_elems: int = 1 << 22) -> int:
        """Checksum of the record stream, chunking-invariant: each entry
        contributes its (user, movie, rating) record bytes in index order
        regardless of how the stream is chunked."""
        rec_t = np.dtype(
            [("u", "<i8"), ("m", "<i8"), ("r", "<f4")]
        )
        crc = 0
        for _, _, u, m, r in self.iter_chunks(chunk_elems):
            rec = np.empty(u.shape[0], dtype=rec_t)
            rec["u"], rec["m"], rec["r"] = u, m, r
            crc = zlib.crc32(rec.tobytes(), crc)
        return crc & 0xFFFFFFFF


def synth_coo(num_users: int, num_movies: int, nnz: int, *, seed: int = 0,
              user_skew: float = 0.7, movie_skew: float = 0.9) -> RatingsCOO:
    """One-call convenience: the whole spec as a ``RatingsCOO``."""
    return PowerLawSynth(SynthSpec(
        num_users=num_users, num_movies=num_movies, nnz=nnz, seed=seed,
        user_skew=user_skew, movie_skew=movie_skew,
    )).coo()
