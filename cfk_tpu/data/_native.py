"""ctypes bindings to the native ingest/codec library (``native/``).

The shared library is optional: ``available()`` is False until
``make -C native`` has produced ``libcfk_native.so`` (or ``build()`` is
called), and every caller falls back to the pure-Python implementation.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from cfk_tpu.data.blocks import RatingsCOO

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libcfk_native.so"))
_IO_ERROR = -0x7FFFFFFF
# Must match cfk_native_abi_version() in native/cfk_native.cpp; a stale .so
# with a different version is treated as unavailable (Python fallback).
_ABI_VERSION = 3

_lib: ctypes.CDLL | None = None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64 = ctypes.c_longlong
    lib.cfk_parse_netflix.restype = i64
    lib.cfk_parse_netflix.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_float),
        i64,
    ]
    lib.cfk_parse_movielens.restype = i64
    lib.cfk_parse_movielens.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_float),
        i64,
        ctypes.c_float,
    ]
    lib.cfk_encode_id_rating_batch.restype = None
    lib.cfk_encode_id_rating_batch.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int16),
        i64,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.cfk_decode_id_rating_batch.restype = i64
    lib.cfk_decode_id_rating_batch.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        i64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int16),
    ]
    lib.cfk_group_by.restype = ctypes.c_int
    lib.cfk_group_by.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        i64,
        i64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.cfk_index_dense.restype = i64
    lib.cfk_index_dense.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        i64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.cfk_native_abi_version.restype = ctypes.c_int
    lib.cfk_native_abi_version.argtypes = []
    return lib


def _try_load() -> None:
    global _lib
    if _lib is not None or not os.path.exists(_LIB_PATH):
        return
    try:
        lib = _bind(ctypes.CDLL(_LIB_PATH))
        if lib.cfk_native_abi_version() == _ABI_VERSION:
            _lib = lib
    except (OSError, AttributeError):
        # AttributeError = stale .so missing a symbol; fall back to Python.
        _lib = None


_try_load()


def available() -> bool:
    return _lib is not None


def build(quiet: bool = True) -> bool:
    """Compile the shared library with make; returns availability."""
    try:
        # Target the .so explicitly: a broken cfk_broker build (e.g. the
        # sockets code on a non-Linux platform) must not disable the parser
        # fast path too.
        subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR), "libcfk_native.so"],
            check=True,
            capture_output=quiet,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False
    _try_load()
    return available()


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _parse(fn, path: str, *extra) -> RatingsCOO:
    assert _lib is not None
    null64 = ctypes.POINTER(ctypes.c_longlong)()
    nullf = ctypes.POINTER(ctypes.c_float)()
    n = fn(path.encode(), null64, null64, nullf, 0, *extra)
    if n == _IO_ERROR:
        raise OSError(f"cannot read {path}")
    if n < 0:
        raise ValueError(f"{path}:{-n}: malformed line")
    movie = np.empty(n, dtype=np.int64)
    user = np.empty(n, dtype=np.int64)
    rating = np.empty(n, dtype=np.float32)
    n2 = fn(
        path.encode(),
        _ptr(movie, ctypes.c_longlong),
        _ptr(user, ctypes.c_longlong),
        _ptr(rating, ctypes.c_float),
        n,
        *extra,
    )
    if n2 != n:
        raise RuntimeError(f"{path}: changed during parse ({n} vs {n2} records)")
    return RatingsCOO(movie_raw=movie, user_raw=user, rating=rating)


def parse_netflix(path: str) -> RatingsCOO:
    return _parse(_lib.cfk_parse_netflix, path)


def parse_movielens(path: str, min_rating: float = 0.0) -> RatingsCOO:
    return _parse(_lib.cfk_parse_movielens, path, ctypes.c_float(min_rating))


def encode_id_rating_batch(ids: np.ndarray, ratings: np.ndarray) -> bytes:
    """Encode n (id, rating) pairs into n 6-byte big-endian wire frames."""
    assert _lib is not None
    ids32 = np.ascontiguousarray(ids, dtype=np.int32)
    r16 = np.ascontiguousarray(ratings, dtype=np.int16)
    out = np.empty(ids32.shape[0] * 6, dtype=np.uint8)
    _lib.cfk_encode_id_rating_batch(
        _ptr(ids32, ctypes.c_int32), _ptr(r16, ctypes.c_int16),
        ids32.shape[0], _ptr(out, ctypes.c_uint8),
    )
    return out.tobytes()


def group_by(keys: np.ndarray, num_keys: int):
    """Stable counting-sort group-by over dense int keys.

    Returns (order int64[nnz], count int32[num_keys], start int64[num_keys])
    with the same semantics as the numpy fallback in
    ``cfk_tpu.data.blocks.group_by_dense``: ``order`` is the stable argsort
    of ``keys``, ``start`` the exclusive prefix sum of ``count``.
    """
    assert _lib is not None
    # Keys stay int64 end-to-end so the C-side [0, num_keys) range check
    # actually fires for corrupt values (an int32 downcast would wrap them
    # into range and group silently wrong).
    k64 = np.ascontiguousarray(keys, dtype=np.int64)
    order = np.empty(k64.shape[0], dtype=np.int64)
    count = np.empty(num_keys, dtype=np.int32)
    start = np.empty(num_keys, dtype=np.int64)
    rc = _lib.cfk_group_by(
        _ptr(k64, ctypes.c_int64), k64.shape[0], num_keys,
        _ptr(order, ctypes.c_int64), _ptr(count, ctypes.c_int32),
        _ptr(start, ctypes.c_int64),
    )
    if rc != 0:
        raise ValueError(f"group_by: key outside [0, {num_keys})")
    return order, count, start


# Raw-id range above which the presence-table indexer would waste memory;
# callers fall back to sort-based indexing (np.unique) past this.
INDEX_DENSE_MAX_RAW = 1 << 28


def index_dense(
    raw: np.ndarray, max_raw: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """(sorted unique ids, dense rank per element) via a presence table.

    O(n + max_raw); requires 0 <= raw <= INDEX_DENSE_MAX_RAW (the caller
    checks and falls back to ``np.unique``-based indexing otherwise).  Pass
    ``max_raw`` when already known to skip a redundant full pass.
    """
    assert _lib is not None
    r64 = np.ascontiguousarray(raw, dtype=np.int64)
    if r64.size == 0:
        return np.empty(0, np.int64), np.empty(0, np.int32)
    if max_raw is None:
        max_raw = int(r64.max())
    cap = min(r64.shape[0], max_raw + 1)
    unique = np.empty(cap, dtype=np.int64)
    dense = np.empty(r64.shape[0], dtype=np.int32)
    n = _lib.cfk_index_dense(
        _ptr(r64, ctypes.c_int64), r64.shape[0], max_raw,
        _ptr(unique, ctypes.c_int64), _ptr(dense, ctypes.c_int32),
    )
    if n < 0:
        raise ValueError("index_dense: negative raw id")
    return unique[:n].copy(), dense


def decode_id_rating_batch(data: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Decode concatenated 6-byte frames → (ids int32, ratings int16)."""
    assert _lib is not None
    buf = np.frombuffer(data, dtype=np.uint8)
    if buf.shape[0] % 6 != 0:
        raise ValueError(f"frame stream length {buf.shape[0]} not a multiple of 6")
    n = buf.shape[0] // 6
    ids = np.empty(n, dtype=np.int32)
    ratings = np.empty(n, dtype=np.int16)
    got = _lib.cfk_decode_id_rating_batch(
        _ptr(buf, ctypes.c_uint8), buf.shape[0],
        _ptr(ids, ctypes.c_int32), _ptr(ratings, ctypes.c_int16),
    )
    if got != n:
        raise ValueError("corrupt frame stream")
    return ids, ratings
