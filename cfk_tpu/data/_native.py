"""ctypes bindings to the native ingest/codec library (``native/``).

The shared library is optional: ``available()`` is False until
``make -C native`` has produced ``libcfk_native.so`` (or ``build()`` is
called), and every caller falls back to the pure-Python implementation.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from cfk_tpu.data.blocks import RatingsCOO

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libcfk_native.so"))
_IO_ERROR = -0x7FFFFFFF
# Must match cfk_native_abi_version() in native/cfk_native.cpp; a stale .so
# with a different version is treated as unavailable (Python fallback).
_ABI_VERSION = 2

_lib: ctypes.CDLL | None = None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64 = ctypes.c_longlong
    lib.cfk_parse_netflix.restype = i64
    lib.cfk_parse_netflix.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_float),
        i64,
    ]
    lib.cfk_parse_movielens.restype = i64
    lib.cfk_parse_movielens.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_float),
        i64,
        ctypes.c_float,
    ]
    lib.cfk_encode_id_rating_batch.restype = None
    lib.cfk_encode_id_rating_batch.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int16),
        i64,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.cfk_decode_id_rating_batch.restype = i64
    lib.cfk_decode_id_rating_batch.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        i64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int16),
    ]
    lib.cfk_native_abi_version.restype = ctypes.c_int
    lib.cfk_native_abi_version.argtypes = []
    return lib


def _try_load() -> None:
    global _lib
    if _lib is not None or not os.path.exists(_LIB_PATH):
        return
    try:
        lib = _bind(ctypes.CDLL(_LIB_PATH))
        if lib.cfk_native_abi_version() == _ABI_VERSION:
            _lib = lib
    except (OSError, AttributeError):
        # AttributeError = stale .so missing a symbol; fall back to Python.
        _lib = None


_try_load()


def available() -> bool:
    return _lib is not None


def build(quiet: bool = True) -> bool:
    """Compile the shared library with make; returns availability."""
    try:
        subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR)],
            check=True,
            capture_output=quiet,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False
    _try_load()
    return available()


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _parse(fn, path: str, *extra) -> RatingsCOO:
    assert _lib is not None
    null64 = ctypes.POINTER(ctypes.c_longlong)()
    nullf = ctypes.POINTER(ctypes.c_float)()
    n = fn(path.encode(), null64, null64, nullf, 0, *extra)
    if n == _IO_ERROR:
        raise OSError(f"cannot read {path}")
    if n < 0:
        raise ValueError(f"{path}:{-n}: malformed line")
    movie = np.empty(n, dtype=np.int64)
    user = np.empty(n, dtype=np.int64)
    rating = np.empty(n, dtype=np.float32)
    n2 = fn(
        path.encode(),
        _ptr(movie, ctypes.c_longlong),
        _ptr(user, ctypes.c_longlong),
        _ptr(rating, ctypes.c_float),
        n,
        *extra,
    )
    if n2 != n:
        raise RuntimeError(f"{path}: changed during parse ({n} vs {n2} records)")
    return RatingsCOO(movie_raw=movie, user_raw=user, rating=rating)


def parse_netflix(path: str) -> RatingsCOO:
    return _parse(_lib.cfk_parse_netflix, path)


def parse_movielens(path: str, min_rating: float = 0.0) -> RatingsCOO:
    return _parse(_lib.cfk_parse_movielens, path, ctypes.c_float(min_rating))


def encode_id_rating_batch(ids: np.ndarray, ratings: np.ndarray) -> bytes:
    """Encode n (id, rating) pairs into n 6-byte big-endian wire frames."""
    assert _lib is not None
    ids32 = np.ascontiguousarray(ids, dtype=np.int32)
    r16 = np.ascontiguousarray(ratings, dtype=np.int16)
    out = np.empty(ids32.shape[0] * 6, dtype=np.uint8)
    _lib.cfk_encode_id_rating_batch(
        _ptr(ids32, ctypes.c_int32), _ptr(r16, ctypes.c_int16),
        ids32.shape[0], _ptr(out, ctypes.c_uint8),
    )
    return out.tobytes()


def decode_id_rating_batch(data: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Decode concatenated 6-byte frames → (ids int32, ratings int16)."""
    assert _lib is not None
    buf = np.frombuffer(data, dtype=np.uint8)
    if buf.shape[0] % 6 != 0:
        raise ValueError(f"frame stream length {buf.shape[0]} not a multiple of 6")
    n = buf.shape[0] // 6
    ids = np.empty(n, dtype=np.int32)
    ratings = np.empty(n, dtype=np.int16)
    got = _lib.cfk_decode_id_rating_batch(
        _ptr(buf, ctypes.c_uint8), buf.shape[0],
        _ptr(ids, ctypes.c_int32), _ptr(ratings, ctypes.c_int16),
    )
    if got != n:
        raise ValueError("corrupt frame stream")
    return ids, ratings
