"""Synthetic Netflix-Prize-shaped rating data for scale benchmarking.

The environment has no network egress, so the full Netflix Prize /
MovieLens-25M files of BASELINE.md cannot be downloaded; throughput at that
scale is instead measured on synthetic data with the same statistical shape:
Zipf-distributed entity popularity (the reference datasets' degree
distributions are power-law — the property that stresses the block layouts)
and uniform 1-5 star ratings.  Quality numbers are only meaningful on the
real bundled samples (``/root/reference/data/``); this module is for
wall-clock / throughput scaling only.
"""

from __future__ import annotations

import numpy as np

from cfk_tpu.data.blocks import RatingsCOO


def zipf_probs(n: int, skew: float) -> np.ndarray:
    p = (1.0 / np.arange(1, n + 1)) ** skew
    return p / p.sum()


def synthetic_netflix_coo(
    num_users: int = 480_189,
    num_movies: int = 17_770,
    nnz: int = 100_480_507,
    *,
    seed: int = 0,
    movie_skew: float = 0.9,
    user_skew: float = 0.7,
) -> RatingsCOO:
    """Netflix-Prize-shaped COO (defaults are the real corpus dimensions).

    Popularity is Zipf over a random permutation of ids (so popular entities
    are scattered across the id space like the real data, not clustered at
    low ids — this matters for contiguous-range sharding load balance).
    Duplicate (movie, user) pairs may occur; ALS treats them as repeated
    observations, which does not change the math's shape or cost.
    """
    rng = np.random.default_rng(seed)
    m_ids = rng.permutation(num_movies).astype(np.int64) + 1
    u_ids = rng.permutation(num_users).astype(np.int64) + 1
    movie = m_ids[rng.choice(num_movies, size=nnz, p=zipf_probs(num_movies, movie_skew))]
    user = u_ids[rng.choice(num_users, size=nnz, p=zipf_probs(num_users, user_skew))]
    rating = rng.integers(1, 6, size=nnz).astype(np.float32)
    return RatingsCOO(movie_raw=movie, user_raw=user, rating=rating)
