"""Synthetic Netflix-Prize-shaped rating data for scale benchmarking.

The environment has no network egress, so the full Netflix Prize /
MovieLens-25M files of BASELINE.md cannot be downloaded; throughput at that
scale is instead measured on synthetic data with the same statistical shape:
Zipf-distributed entity popularity (the reference datasets' degree
distributions are power-law — the property that stresses the block layouts)
and uniform 1-5 star ratings.  Quality numbers are only meaningful on the
real bundled samples (``/root/reference/data/``); this module is for
wall-clock / throughput scaling only.
"""

from __future__ import annotations

import numpy as np

from cfk_tpu.data.blocks import RatingsCOO


def zipf_probs(n: int, skew: float) -> np.ndarray:
    p = (1.0 / np.arange(1, n + 1)) ** skew
    return p / p.sum()


def synthetic_netflix_coo(
    num_users: int = 480_189,
    num_movies: int = 17_770,
    nnz: int = 100_480_507,
    *,
    seed: int = 0,
    movie_skew: float = 0.9,
    user_skew: float = 0.7,
) -> RatingsCOO:
    """Netflix-Prize-shaped COO (defaults are the real corpus dimensions).

    Popularity is Zipf over a random permutation of ids (so popular entities
    are scattered across the id space like the real data, not clustered at
    low ids — this matters for contiguous-range sharding load balance).
    Duplicate (movie, user) pairs may occur; ALS treats them as repeated
    observations, which does not change the math's shape or cost.
    """
    rng = np.random.default_rng(seed)
    m_ids = rng.permutation(num_movies).astype(np.int64) + 1
    u_ids = rng.permutation(num_users).astype(np.int64) + 1
    movie = m_ids[rng.choice(num_movies, size=nnz, p=zipf_probs(num_movies, movie_skew))]
    user = u_ids[rng.choice(num_users, size=nnz, p=zipf_probs(num_users, user_skew))]
    rating = rng.integers(1, 6, size=nnz).astype(np.float32)
    return RatingsCOO(movie_raw=movie, user_raw=user, rating=rating)


def planted_factor_coo(
    num_users: int,
    num_movies: int,
    nnz: int,
    *,
    rank: int,
    noise: float = 0.1,
    heldout: int = 0,
    seed: int = 0,
    movie_skew: float = 0.9,
    user_skew: float = 0.7,
) -> tuple[RatingsCOO, RatingsCOO | None]:
    """Ratings generated from KNOWN low-rank factors plus Gaussian noise.

    The quality validation for shapes whose real corpus is unfetchable
    (VERDICT r1 item #6): plant U* [users, rank], M* [movies, rank] with
    entries N(0, rank^-1/4) — so the rank-term dot product u*·m* has unit
    variance and planted ratings are O(1) — and emit
    r = u*·m* + ε, ε ~ N(0, noise²), at Zipf-popular (user, movie) pairs.
    A correctly working at-scale pipeline (layout + bf16 storage + pallas
    solver + sharding) must drive held-out RMSE down toward the noise
    floor σ; a subtly broken one cannot.  Returns (train COO, heldout COO)
    — ``heldout`` extra planted cells never seen in training (None if 0).
    """
    rng = np.random.default_rng(seed)
    u_star = rng.standard_normal((num_users, rank)).astype(np.float32)
    m_star = rng.standard_normal((num_movies, rank)).astype(np.float32)
    u_star /= rank ** 0.25
    m_star /= rank ** 0.25
    m_ids = rng.permutation(num_movies).astype(np.int64) + 1
    u_ids = rng.permutation(num_users).astype(np.int64) + 1
    total = nnz + heldout
    m_idx = rng.choice(num_movies, size=total, p=zipf_probs(num_movies, movie_skew))
    u_idx = rng.choice(num_users, size=total, p=zipf_probs(num_users, user_skew))
    # Chunked dot products: unchunked [total, rank] gathers would spike
    # ~52 GB host RAM at the full Netflix shape.
    r = np.empty(total, dtype=np.float32)
    chunk = 1 << 22
    for lo in range(0, total, chunk):
        sl = slice(lo, lo + chunk)
        r[sl] = np.einsum(
            "nk,nk->n", u_star[u_idx[sl]], m_star[m_idx[sl]]
        )
    r += (noise * rng.standard_normal(total)).astype(np.float32)
    train = RatingsCOO(
        movie_raw=m_ids[m_idx[:nnz]], user_raw=u_ids[u_idx[:nnz]],
        rating=r[:nnz],
    )
    if heldout == 0:
        return train, None
    # Held-out cells must be UNSEEN: Zipf-hot (user, movie) pairs are drawn
    # many times, so i.i.d. held-out draws collide with training pairs and
    # ALS would partially fit their noise — drop the collisions (this skews
    # the held-out set toward cold pairs, i.e. the CONSERVATIVE direction
    # for the recovery bound).
    key = u_idx.astype(np.int64) * num_movies + m_idx
    fresh = ~np.isin(key[nnz:], key[:nnz], kind="sort")
    held = RatingsCOO(
        movie_raw=m_ids[m_idx[nnz:]][fresh], user_raw=u_ids[u_idx[nnz:]][fresh],
        rating=r[nnz:][fresh],
    )
    return train, held
