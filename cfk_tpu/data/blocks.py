"""Rating blocks: the TPU-native analog of the reference's InBlocks/OutBlocks.

The reference materializes, per Kafka partition, three state stores per side —
neighbor-id lists, rating lists, and the set of partitions that need each
factor vector (``processors/MRatings2BlocksProcessor.java:46-69`` and the
user-side mirror).  On TPU the same information becomes dense arrays:

- ``IdMap``           — sparse external ids ↔ dense ascending indices (the
                        reference keeps raw ids as Kafka keys throughout and
                        only sorts at the final collector's TreeMap,
                        ``processors/FeatureCollector.java:64-70``; we sort
                        once up front so factor row i ↔ i-th smallest raw id).
- ``PaddedBlocks``    — per-entity ragged neighbor lists padded to a rectangle
                        [num_entities_padded, max_nnz_padded]: neighbor dense
                        indices, ratings, and a validity mask.  This is the
                        InBlock, laid out for one big MXU-friendly gather +
                        batched matmul instead of per-entity HashMap
                        accumulation (``processors/MFeatureCalculator.java:56-74``).
- OutBlocks have no explicit analog: with ``all_gather`` every shard sees all
  fixed-side factors (dedup-per-partition comes free, SURVEY.md §2.6), and the
  ring exchange passes whole factor shards, so "who needs my vector" is never
  tracked per entity.

Entity-count padding rows (mask all zero, count 0) make every shard the same
size; their normal equations are made non-singular by clamping the ALS-WR
regularizer ``λ·n`` to a floor of 1 for n == 0 rows (real rows always have
n ≥ 1 so their math is untouched — exact reference semantics,
``processors/MFeatureCalculator.java:91-95``).
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np


@dataclasses.dataclass(frozen=True)
class RatingsCOO:
    """All ratings as parallel COO arrays (raw external ids)."""

    movie_raw: np.ndarray  # int64 [nnz]
    user_raw: np.ndarray  # int64 [nnz]
    rating: np.ndarray  # float32 [nnz]

    @property
    def num_ratings(self) -> int:
        return int(self.rating.shape[0])


@dataclasses.dataclass(frozen=True)
class IdMap:
    """Sorted unique raw ids; dense index i ↔ ``raw_ids[i]`` (ascending).

    Only *rated* entities are included, matching the reference's counting
    (SURVEY.md §6: NUM_MOVIES/NUM_USERS count rated entities; prediction
    matrix rows/cols are ascending-id over those).
    """

    raw_ids: np.ndarray  # int64 [num_entities], sorted ascending

    @classmethod
    def from_raw(cls, raw: np.ndarray) -> "IdMap":
        return cls(raw_ids=np.unique(raw))

    @property
    def num_entities(self) -> int:
        return int(self.raw_ids.shape[0])

    def to_dense(self, raw: np.ndarray) -> np.ndarray:
        """Map raw ids → dense indices. Raises if any raw id is unknown."""
        idx = np.searchsorted(self.raw_ids, raw)
        if np.any(idx >= self.num_entities) or np.any(self.raw_ids[idx] != raw):
            bad = raw[(idx >= self.num_entities) | (self.raw_ids[np.minimum(idx, self.num_entities - 1)] != raw)]
            raise KeyError(f"unknown raw ids, e.g. {bad[:5]}")
        return idx.astype(np.int32)


def _round_up(x: int, multiple: int) -> int:
    return ((x + multiple - 1) // multiple) * multiple


def group_by_dense(keys: np.ndarray, num_keys: int):
    """(stable argsort order, per-key counts int32, exclusive-prefix starts).

    The grouping step every block builder shares.  Dense keys admit an
    O(n + k) counting sort — done in native code when the library is built
    (``native/cfk_native.cpp`` ``cfk_group_by``); the numpy fallback is the
    O(n log n) comparison argsort.
    """
    if 0 < num_keys < (1 << 31):
        from cfk_tpu.data import _native

        if _native.available():
            return _native.group_by(keys, num_keys)
    order = np.argsort(keys, kind="stable")
    count = np.bincount(keys, minlength=num_keys).astype(np.int32)
    start = np.zeros(num_keys, dtype=np.int64)
    np.cumsum(count[:-1], out=start[1:])
    return order, count, start


def index_entities(raw: np.ndarray) -> tuple[IdMap, np.ndarray]:
    """(IdMap of the distinct raw ids, dense index per element).

    Native presence-table indexing (O(n + max_raw)) when ids are small
    non-negative ints — true of every rating dataset here; sort-based
    ``np.unique``/``searchsorted`` otherwise.  The table is gated on the id
    range both absolutely and relative to nnz (a tiny file with huge sparse
    ids would otherwise pay an O(max_raw) scan for nothing); negative ids
    are caught by the C-side range check.
    """
    if raw.size:
        from cfk_tpu.data import _native

        if _native.available():
            max_raw = int(raw.max())
            if 0 <= max_raw <= min(
                _native.INDEX_DENSE_MAX_RAW, 64 * raw.size + (1 << 16)
            ):
                try:
                    unique, dense = _native.index_dense(raw, max_raw)
                except ValueError:
                    pass  # negative ids: fall through to the sort path
                else:
                    return IdMap(raw_ids=unique), dense
    id_map = IdMap.from_raw(raw)
    return id_map, id_map.to_dense(raw)


@dataclasses.dataclass(frozen=True)
class PaddedBlocks:
    """Rectangular InBlocks for one solve side.

    Row e (< ``num_entities``) holds entity e's neighbors; rows beyond are
    all-padding so the entity axis divides ``num_shards`` evenly.
    """

    neighbor_idx: np.ndarray  # int32 [E_pad, P] dense idx into the fixed side (0 where masked)
    rating: np.ndarray  # float32 [E_pad, P] (0 where masked)
    mask: np.ndarray  # float32 [E_pad, P] 1.0 = real rating
    count: np.ndarray  # int32 [E_pad] real nnz per entity (0 for pad rows)
    num_entities: int  # real (un-padded) entity count

    @property
    def padded_entities(self) -> int:
        return int(self.neighbor_idx.shape[0])

    @property
    def max_nnz(self) -> int:
        return int(self.neighbor_idx.shape[1])


def build_padded_blocks(
    solve_dense: np.ndarray,
    fixed_dense: np.ndarray,
    rating: np.ndarray,
    num_solve_entities: int,
    *,
    num_shards: int = 1,
    pad_multiple: int = 8,
) -> PaddedBlocks:
    """Group ratings by the solve-side entity into a padded rectangle.

    ``solve_dense``/``fixed_dense`` are dense indices (from ``IdMap.to_dense``)
    of the side being solved / held fixed.  Fully vectorized (no Python loop
    over entities); the reference does the equivalent incrementally per record
    in ``MRatings2BlocksProcessor``/``URatings2BlocksProcessor``.
    """
    nnz = solve_dense.shape[0]
    order, count, group_start = group_by_dense(solve_dense, num_solve_entities)
    s_sorted = solve_dense[order]
    f_sorted = fixed_dense[order].astype(np.int32)
    r_sorted = rating[order].astype(np.float32)

    max_nnz = _round_up(max(int(count.max()), 1), pad_multiple)
    e_pad = _round_up(num_solve_entities, num_shards)

    # Position of each rating within its entity's group.
    pos = np.arange(nnz, dtype=np.int64) - group_start[s_sorted]

    neighbor = np.zeros((e_pad, max_nnz), dtype=np.int32)
    rmat = np.zeros((e_pad, max_nnz), dtype=np.float32)
    mask = np.zeros((e_pad, max_nnz), dtype=np.float32)
    neighbor[s_sorted, pos] = f_sorted
    rmat[s_sorted, pos] = r_sorted
    mask[s_sorted, pos] = 1.0

    count_pad = np.zeros(e_pad, dtype=np.int32)
    count_pad[:num_solve_entities] = count
    return PaddedBlocks(
        neighbor_idx=neighbor,
        rating=rmat,
        mask=mask,
        count=count_pad,
        num_entities=num_solve_entities,
    )


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One width class of a ``BucketedBlocks``: entities whose nnz fits ``width``.

    Rows are shard-major: shard s owns rows [s·B, (s+1)·B) where
    B = rows/num_shards, so a ``P("shard", None)`` sharding hands each device
    exactly its own entities.  ``entity_local`` maps each row to the entity's
    index *within its shard's factor slice*; padding rows point at the trash
    slot ``local_entities`` (one past the real rows).
    """

    neighbor_idx: np.ndarray  # int32 [rows, width] dense idx into the fixed side
    rating: np.ndarray  # float32 [rows, width]
    mask: np.ndarray  # float32 [rows, width]
    count: np.ndarray  # int32 [rows]
    entity_local: np.ndarray  # int32 [rows]
    chunk_rows: int | None  # static per-shard chunking hint (divides rows/S)

    @property
    def width(self) -> int:
        return int(self.neighbor_idx.shape[1])


@dataclasses.dataclass(frozen=True)
class BucketedBlocks:
    """InBlocks grouped into power-of-two width classes (the ALX layout).

    A single ``PaddedBlocks`` rectangle pads every entity to the global max
    nnz — quadratic waste under the power-law degree distributions of real
    rating data (one 200k-rating movie would force a [17k, 200k] rectangle).
    Here entities are binned by nnz into buckets of width pad_multiple·2^j;
    each bucket is its own small rectangle, so total padded cells stay within
    2× of nnz.  Entities with zero ratings get no row at all: their solve is
    identically zero (the reference's HashMap likewise only ever holds rated
    entities, ``processors/MFeatureCalculator.java:56-65``).
    """

    buckets: tuple[Bucket, ...]
    count: np.ndarray  # int32 [E_pad] dense per-entity nnz (0 for pad rows)
    rating_sum: np.ndarray  # float32 [E_pad] per-entity rating sum (for init)
    num_entities: int
    num_shards: int

    @property
    def padded_entities(self) -> int:
        return int(self.count.shape[0])

    @property
    def local_entities(self) -> int:
        return self.padded_entities // self.num_shards

    @property
    def padded_cells(self) -> int:
        return sum(b.neighbor_idx.size for b in self.buckets)

    def to_tree(self):
        """(tuple-of-dicts pytree of bucket arrays, static chunk hints).

        The single source of the bucket-dict field list — device placement
        and sharding specs are derived from this shape.
        """
        trees = tuple(
            {
                "neighbor": b.neighbor_idx,
                "rating": b.rating,
                "mask": b.mask,
                "count": b.count,
                "entity_local": b.entity_local,
            }
            for b in self.buckets
        )
        return trees, tuple(b.chunk_rows for b in self.buckets)


def build_bucketed_blocks(
    solve_dense: np.ndarray,
    fixed_dense: np.ndarray,
    rating: np.ndarray,
    num_solve_entities: int,
    *,
    num_shards: int = 1,
    pad_multiple: int = 8,
    chunk_elems: int | None = 1 << 20,
) -> BucketedBlocks:
    """Bin entities into power-of-two width buckets, shard-major rows.

    ``chunk_elems`` bounds rows·width per solve chunk: buckets whose per-shard
    row count exceeds ``chunk_elems // width`` get a static ``chunk_rows``
    hint (and rows padded to a multiple of it) so the device-side gather is
    streamed through HBM in bounded pieces.
    """
    e_pad = _round_up(num_solve_entities, num_shards)
    e_local = e_pad // num_shards
    order, count, group_start = group_by_dense(solve_dense, num_solve_entities)
    s_sorted = solve_dense[order]
    f_sorted = fixed_dense[order].astype(np.int32)
    r_sorted = rating[order].astype(np.float32)
    pos = np.arange(s_sorted.shape[0], dtype=np.int64) - group_start[s_sorted]

    max_nnz = max(int(count.max()), 1)
    widths = [pad_multiple]
    while widths[-1] < max_nnz:
        widths.append(widths[-1] * 2)

    bucket_of = np.searchsorted(widths, count)  # smallest j with width_j >= nnz
    shard_of = np.arange(num_solve_entities, dtype=np.int64) // e_local
    rated = count > 0

    # Per-bucket geometry first (O(E) work per bucket), then ONE flat-arena
    # scatter for all ratings: per-bucket boolean scans over the nnz axis
    # would cost O(buckets · nnz) — the builder's former hot spot at
    # 100M-rating scale.
    metas = []  # (bucket j, width, rows, chunk, ents, rows_idx, arena offset)
    arena_cells = 0
    # flat arena position of each entity's (row, col 0) cell
    entity_base = np.full(num_solve_entities, -1, dtype=np.int64)
    for j, width in enumerate(widths):
        ents = np.flatnonzero(rated & (bucket_of == j))
        if ents.size == 0:
            continue
        sh = shard_of[ents]
        per_shard = np.bincount(sh, minlength=num_shards)
        b = int(per_shard.max())
        chunk = None
        if chunk_elems is not None:
            cap = max(1, chunk_elems // width)
            if b > cap:
                chunk = cap
                b = _round_up(b, cap)
        rows = num_shards * b
        # ents ascend in dense-id order, so they ascend in shard order too;
        # position within each shard's run = index − first index of that run.
        idx_in_shard = np.arange(ents.size) - np.searchsorted(sh, sh)
        rows_idx = sh * b + idx_in_shard
        entity_base[ents] = arena_cells + rows_idx * width
        metas.append((width, rows, chunk, ents, rows_idx, arena_cells))
        arena_cells += rows * width

    neighbor_arena = np.zeros(arena_cells, dtype=np.int32)
    rating_arena = np.zeros(arena_cells, dtype=np.float32)
    mask_arena = np.zeros(arena_cells, dtype=np.float32)
    target = entity_base[s_sorted] + pos
    neighbor_arena[target] = f_sorted
    rating_arena[target] = r_sorted
    mask_arena[target] = 1.0

    buckets = []
    for width, rows, chunk, ents, rows_idx, off in metas:
        count_rows = np.zeros(rows, dtype=np.int32)
        entity_local = np.full(rows, e_local, dtype=np.int32)
        count_rows[rows_idx] = count[ents]
        entity_local[rows_idx] = (ents % e_local).astype(np.int32)
        cells = slice(off, off + rows * width)
        buckets.append(
            Bucket(
                neighbor_idx=neighbor_arena[cells].reshape(rows, width),
                rating=rating_arena[cells].reshape(rows, width),
                mask=mask_arena[cells].reshape(rows, width),
                count=count_rows,
                entity_local=entity_local,
                chunk_rows=chunk,
            )
        )

    count_pad = np.zeros(e_pad, dtype=np.int32)
    count_pad[:num_solve_entities] = count
    rating_sum = np.zeros(e_pad, dtype=np.float32)
    rating_sum[:num_solve_entities] = np.bincount(
        solve_dense, weights=rating.astype(np.float64), minlength=num_solve_entities
    ).astype(np.float32)
    return BucketedBlocks(
        buckets=tuple(buckets),
        count=count_pad,
        rating_sum=rating_sum,
        num_entities=num_solve_entities,
        num_shards=num_shards,
    )


@dataclasses.dataclass(frozen=True)
class SegmentBlocks:
    """Flat CSR-style InBlocks packed into fixed-size chunks.

    The third layout for the ragged-InBlock problem (SURVEY.md §5 long-context
    analog): instead of padding entities into rectangles (``PaddedBlocks``) or
    width classes (``BucketedBlocks``), ratings stay flat sorted runs and the
    per-entity Gram matrices are accumulated by sorted grouped matmul
    (``lax.ragged_dot_general`` on the MXU, ``segment_sum`` fallback) —
    O(nnz) memory regardless of the degree distribution.

    Each shard's sorted run is cut into ``num_chunks`` chunks of ≤
    ``chunk_cap`` ratings covering ≤ ``chunk_entities`` consecutive entities
    (dense ids are compact — every ``IdMap`` id has ≥ 1 rating — so an
    entity range IS a contiguous rating slice).  **Entities may straddle
    chunk boundaries**: a hot entity with more ratings than ``chunk_cap``
    spans several chunks, and the solve scan carries its partial Gram/RHS
    across them (``carry_in`` flags the continuation; ``last_seg`` indexes
    the straddling segment).  Chunk capacity is therefore independent of the
    maximum degree — the property that makes the layout robust to
    arbitrarily skewed data, where the old entity-boundary packing inflated
    every chunk to the hottest entity's degree.  The solve scans over
    chunks, so device memory for the Gram accumulator is
    O(chunk_entities·k²), never O(E·k²): at full-Netflix scale the
    unchunked user-side accumulator alone (480k·64² floats ≈ 8 GB) exceeds
    single-chip HBM.  Entries are shard-major ⇒ every array shards as
    ``P("shard")``.

    ``seg_rel`` holds each rating's entity index *relative to its chunk's
    first entity* (padding entries use the ``chunk_entities`` trash row);
    ``chunk_entity``/``chunk_count`` give each chunk row's shard-local
    entity id and rating count — ``local_entities`` (trash) for rows whose
    entity is *not finalized* in that chunk (straddlers continuing into the
    next chunk, and padding rows).
    """

    neighbor_idx: np.ndarray  # int32 [S·NC·C] dense idx into the fixed side (0 at padding)
    rating: np.ndarray  # float32 [S·NC·C] (0 at padding)
    mask: np.ndarray  # float32 [S·NC·C] 1.0 = real rating
    seg_rel: np.ndarray  # int32 [S·NC·C] chunk-relative entity row, sorted per chunk
    chunk_entity: np.ndarray  # int32 [S·NC·Ec] shard-local entity row (e_local = trash)
    chunk_count: np.ndarray  # int32 [S·NC·Ec] full rating count of finalized rows (0 else)
    group_sizes: np.ndarray  # int32 [S·NC·(Ec+1)] physical entries per segment (trash last)
    carry_in: np.ndarray  # float32 [S·NC] 1.0 = chunk's seg 0 continues the previous chunk
    last_seg: np.ndarray  # int32 [S·NC] chunk-relative index of the last real segment
    chunk_first: np.ndarray  # int32 [S·NC] shard-local entity id of each chunk's seg 0
    count: np.ndarray  # int32 [E_pad] real nnz per entity (0 for pad rows)
    rating_sum: np.ndarray  # float32 [E_pad] per-entity rating sum (for init)
    num_entities: int
    num_shards: int
    num_chunks: int  # NC: chunks per shard
    chunk_cap: int  # C: ratings per chunk (padded)
    chunk_entities: int  # Ec: entity rows per chunk (padded)

    @property
    def padded_entities(self) -> int:
        return int(self.count.shape[0])

    @property
    def local_entities(self) -> int:
        return self.padded_entities // self.num_shards

    @property
    def nnz_per_shard(self) -> int:
        return self.num_chunks * self.chunk_cap

    @property
    def statics(self) -> tuple[int, int, int]:
        """(num_chunks, chunk_cap, chunk_entities) — the jit-static shape
        triple the segment solve kernels need."""
        return (self.num_chunks, self.chunk_cap, self.chunk_entities)


def build_segment_blocks(
    solve_dense: np.ndarray,
    fixed_dense: np.ndarray,
    rating: np.ndarray,
    num_solve_entities: int,
    *,
    num_shards: int = 1,
    pad_multiple: int = 8,
    chunk_nnz: int | None = None,
    chunk_entity_cap: int | None = None,
) -> SegmentBlocks:
    """Sort ratings by (shard, local entity row) and pack into nnz chunks.

    ``chunk_nnz`` is the ratings-per-chunk capacity, bounding the per-chunk
    gather; a chunk also covers at most ``chunk_entity_cap`` consecutive
    entities (default ``min(chunk_nnz // 32, 16384)``), bounding the
    [Ec, k, k] Gram accumulator even on all-degree-1 runs.  Entities whose
    degree exceeds the capacity **straddle chunks** — the solve scan carries
    their partial Gram across the boundary — so the capacity never inflates
    with the degree distribution's head.  ``None`` packs each shard into a
    single chunk (fine until the per-shard entity count × k² outgrows HBM).
    """
    e_pad = _round_up(num_solve_entities, num_shards)
    e_local = e_pad // num_shards
    order, count, _ = group_by_dense(solve_dense, num_solve_entities)
    s_sorted = solve_dense[order].astype(np.int64)
    f_sorted = fixed_dense[order].astype(np.int32)
    r_sorted = rating[order].astype(np.float32)
    local_sorted = (s_sorted % e_local).astype(np.int32)

    count_pad = np.zeros(e_pad, dtype=np.int32)
    count_pad[:num_solve_entities] = count
    counts_local = count_pad.reshape(num_shards, e_local)
    per_shard_nnz = counts_local.sum(axis=1, dtype=np.int64)
    shard_start = np.zeros(num_shards, dtype=np.int64)
    np.cumsum(per_shard_nnz[:-1], out=shard_start[1:])
    # Rated local entities are consecutive from 0 (compact dense ids; only
    # the global-pad tail of the last shard is unrated).

    if chunk_nnz is None:
        cap = max(int(per_shard_nnz.max()), 1, pad_multiple)
        e_cap = max(e_local, 1)
    else:
        # Never pad a chunk beyond the largest shard's actual run.
        cap = max(min(int(chunk_nnz), int(per_shard_nnz.max())), pad_multiple)
        if chunk_entity_cap is not None:
            e_cap = max(int(chunk_entity_cap), 1)
        else:
            e_cap = max(1, min(cap // 32, 1 << 14))
    cap = _round_up(cap, pad_multiple)

    # Greedy nnz packing per shard: cut the sorted run every ``cap`` entries
    # (or sooner when the slice would span more than ``e_cap`` entities).
    # Cuts may fall inside an entity's run — that entity straddles chunks.
    shard_cuts: list[list[tuple[int, int]]] = []
    for s in range(num_shards):
        lo = int(shard_start[s])
        hi = lo + int(per_shard_nnz[s])
        # cum[e] = shard-run position of entity e's first entry
        cum = np.zeros(e_local + 1, dtype=np.int64)
        np.cumsum(counts_local[s], out=cum[1:])
        cuts = []
        pos = lo
        while pos < hi:
            end = min(pos + cap, hi)
            first = int(local_sorted[pos])
            if int(local_sorted[end - 1]) - first + 1 > e_cap:
                end = lo + int(cum[first + e_cap])
            cuts.append((pos, end))
            pos = end
        shard_cuts.append(cuts)

    num_chunks = max(max((len(c) for c in shard_cuts), default=1), 1)
    e_c = 1
    for cuts in shard_cuts:
        for p0, p1 in cuts:
            e_c = max(e_c, int(local_sorted[p1 - 1]) - int(local_sorted[p0]) + 1)

    neighbor = np.zeros(num_shards * num_chunks * cap, dtype=np.int32)
    rmat = np.zeros(num_shards * num_chunks * cap, dtype=np.float32)
    mask = np.zeros(num_shards * num_chunks * cap, dtype=np.float32)
    seg = np.full(num_shards * num_chunks * cap, e_c, dtype=np.int32)  # trash
    chunk_entity = np.full(num_shards * num_chunks * e_c, e_local, dtype=np.int32)
    chunk_count = np.zeros(num_shards * num_chunks * e_c, dtype=np.int32)
    group_sizes = np.zeros(num_shards * num_chunks * (e_c + 1), dtype=np.int32)
    # All-padding chunks are one full trash segment.
    group_sizes.reshape(-1, e_c + 1)[:, e_c] = cap
    carry_in = np.zeros(num_shards * num_chunks, dtype=np.float32)
    last_seg = np.zeros(num_shards * num_chunks, dtype=np.int32)
    chunk_first = np.zeros(num_shards * num_chunks, dtype=np.int32)

    for s in range(num_shards):
        lo = int(shard_start[s])
        hi = lo + int(per_shard_nnz[s])
        for c, (p0, p1) in enumerate(shard_cuts[s]):
            n = p1 - p0
            ci = s * num_chunks + c
            dst = ci * cap
            first = int(local_sorted[p0])
            last = int(local_sorted[p1 - 1])
            neighbor[dst : dst + n] = f_sorted[p0:p1]
            rmat[dst : dst + n] = r_sorted[p0:p1]
            mask[dst : dst + n] = 1.0
            seg_chunk = (local_sorted[p0:p1] - first).astype(np.int64)
            seg[dst : dst + n] = seg_chunk
            sizes = np.bincount(seg_chunk, minlength=e_c + 1).astype(np.int32)
            sizes[e_c] = cap - n  # tail padding sits in the trash segment
            group_sizes[ci * (e_c + 1) : (ci + 1) * (e_c + 1)] = sizes
            carry_in[ci] = float(p0 > lo and int(local_sorted[p0 - 1]) == first)
            last_seg[ci] = last - first
            chunk_first[ci] = first
            # Rows are finalized here unless the last entity continues into
            # the next chunk; only the finalizing chunk writes the output row.
            cont_out = p1 < hi and int(local_sorted[p1]) == last
            n_final = (last - first + 1) - int(cont_out)
            if n_final > 0:
                ebase = ci * e_c
                chunk_entity[ebase : ebase + n_final] = np.arange(
                    first, first + n_final, dtype=np.int32
                )
                chunk_count[ebase : ebase + n_final] = counts_local[
                    s, first : first + n_final
                ]

    rating_sum = np.zeros(e_pad, dtype=np.float32)
    rating_sum[:num_solve_entities] = np.bincount(
        solve_dense, weights=rating.astype(np.float64), minlength=num_solve_entities
    ).astype(np.float32)
    return SegmentBlocks(
        neighbor_idx=neighbor,
        rating=rmat,
        mask=mask,
        seg_rel=seg,
        chunk_entity=chunk_entity,
        chunk_count=chunk_count,
        group_sizes=group_sizes,
        carry_in=carry_in,
        last_seg=last_seg,
        chunk_first=chunk_first,
        count=count_pad,
        rating_sum=rating_sum,
        num_entities=num_solve_entities,
        num_shards=num_shards,
        num_chunks=num_chunks,
        chunk_cap=cap,
        chunk_entities=e_c,
    )


@dataclasses.dataclass(frozen=True)
class RingBlocks:
    """Per-fixed-shard InBlocks for the ring (block-to-block join) exchange.

    ``neighbor_local[e, t, p]`` is the index *within fixed shard t's row block*
    of entity e's p-th neighbor owned by shard t (contiguous sharding: fixed
    shard t owns dense rows [t·Fs, (t+1)·Fs)).  At ring step r a device holds
    one fixed-side row block and accumulates that block's partial Gram
    contribution — the TPU analog of the reference's block-to-block join
    (README.md:152-157): each factor block moves once per shard pair instead
    of every vector moving per dependent row.
    """

    neighbor_local: np.ndarray  # int32 [E_pad, S, P_ring]
    rating: np.ndarray  # float32 [E_pad, S, P_ring]
    mask: np.ndarray  # float32 [E_pad, S, P_ring]
    count: np.ndarray  # int32 [E_pad] total real nnz per entity
    num_entities: int
    fixed_shard_size: int  # Fs = padded fixed-entity count / num_shards

    @property
    def num_shards(self) -> int:
        return int(self.neighbor_local.shape[1])


def build_ring_blocks(
    solve_dense: np.ndarray,
    fixed_dense: np.ndarray,
    rating: np.ndarray,
    num_solve_entities: int,
    num_fixed_entities: int,
    *,
    num_shards: int,
    pad_multiple: int = 8,
) -> RingBlocks:
    """Split each entity's neighbor list by the fixed shard owning the neighbor.

    Returns rectangles [E_pad, S, P_ring] where P_ring = max ratings any
    (entity, fixed-shard) pair holds, rounded up to ``pad_multiple``.
    """
    f_pad = _round_up(num_fixed_entities, num_shards)
    fs = f_pad // num_shards
    shard_of = (fixed_dense // fs).astype(np.int64)
    local = (fixed_dense % fs).astype(np.int32)

    e_pad = _round_up(num_solve_entities, num_shards)
    # Group key = (solve entity, fixed shard); stable sort then position-in-group.
    key = solve_dense.astype(np.int64) * num_shards + shard_of
    order, pair_count, group_start = group_by_dense(
        key, num_solve_entities * num_shards
    )
    key_s = key[order]
    p_ring = _round_up(max(int(pair_count.max()), 1), pad_multiple)
    pos = np.arange(key_s.shape[0], dtype=np.int64) - group_start[key_s]

    e_idx = key_s // num_shards
    t_idx = key_s % num_shards
    neighbor = np.zeros((e_pad, num_shards, p_ring), dtype=np.int32)
    rmat = np.zeros((e_pad, num_shards, p_ring), dtype=np.float32)
    mask = np.zeros((e_pad, num_shards, p_ring), dtype=np.float32)
    neighbor[e_idx, t_idx, pos] = local[order]
    rmat[e_idx, t_idx, pos] = rating[order].astype(np.float32)
    mask[e_idx, t_idx, pos] = 1.0

    count = np.zeros(e_pad, dtype=np.int32)
    count[:num_solve_entities] = np.bincount(
        solve_dense, minlength=num_solve_entities
    ).astype(np.int32)
    return RingBlocks(
        neighbor_local=neighbor,
        rating=rmat,
        mask=mask,
        count=count,
        num_entities=num_solve_entities,
        fixed_shard_size=fs,
    )


@dataclasses.dataclass(frozen=True)
class TiledBlocks:
    """Tile-padded InBlocks: the MXU-native segment layout (see
    ``cfk_tpu.ops.tiled`` for the measured rationale).

    Every entity's rating run is padded (weight-0 entries) to a multiple of
    ``tile_rows``, so the flat stream is an exact grid of [tile_rows]-entry
    tiles each owned by one entity: per-entity Grams become a batched tile
    GEMM + a segment-sum over ~3 tiles/entity instead of a ragged matmul
    over ~200-entry segments.  Two modes:

    - ``mode="stream"`` (many entities): chunk-scan with per-chunk
      finalization and a carried partial Gram for boundary-straddling
      entities — the ``SegmentBlocks`` structure at tile granularity.
    - ``mode="accum"`` (few entities, big fixed table): entries sorted by
      (fixed-table slice of ``slice_rows`` rows, entity), chunks never span
      a slice, ``chunk_base`` gives each chunk's table slice offset, and
      the solve accumulates all chunks into one [E+1, k, k] carry — this is
      what keeps the factor gather on XLA's fast small-table path (the
      480k-row table gathers 4× slower than any ≤34 MB slice of it).

    Entries are shard-major; every flat array shards as ``P("shard")``.
    """

    neighbor_idx: np.ndarray  # int32 [S·NC·C]; accum mode: SLICE-local rows
    rating: np.ndarray  # float32 [S·NC·C] b-coefficient (0 at padding)
    weight: np.ndarray  # float32 [S·NC·C] A-weight (0 at padding)
    tile_seg: np.ndarray  # int32 [S·NC·NT] chunk-relative/-dense entity of each tile (trash = Ec)
    chunk_base: np.ndarray  # int32 [S·NC] accum: table slice offset (0 in stream mode)
    chunk_entity: np.ndarray  # int32 [S·NC·Ec] stream: finalization rows; accum: rank→entity list
    chunk_count: np.ndarray  # int32 [S·NC·Ec]
    carry_in: np.ndarray  # float32 [S·NC]
    last_seg: np.ndarray  # int32 [S·NC]
    slice_starts: np.ndarray  # int32 [S·(n_slices+1)] accum: chunk range per slice
    count: np.ndarray  # int32 [E_pad]
    rating_sum: np.ndarray  # float32 [E_pad]
    mode: str  # "stream" | "accum"
    num_entities: int
    num_shards: int
    num_chunks: int  # NC
    chunk_cap: int  # C (entries per chunk, multiple of tile_rows)
    chunk_entities: int  # Ec (stream mode; 0 in accum)
    tile_rows: int  # T
    slice_rows: int  # H (gather-slice height; = padded fixed rows if unsliced)
    num_slices: int = 1  # accum: fixed-table slices (ring: = num_shards)
    ring: bool = False  # built for the ppermute ring exchange
    # Dense-stream mode ("dstream") only — see _build_dense_stream:
    tile_meta: np.ndarray | None = None  # int32 [S·NC·(NG+4·NT)]
    rating_dense: np.ndarray | None = None  # f32 [S·NC·C] stream-aligned
    # per-entry ratings (the weighted path's A-weight source; 0 at pad)
    num_tiles: int = 0  # NT (tile slots per chunk, = NG·group_tiles)
    num_groups: int = 0  # NG (kernel grid steps per chunk)
    block_rows: int = 0  # BG (gather-stream rows per pipelined block)

    @property
    def padded_entities(self) -> int:
        return int(self.count.shape[0])

    @property
    def local_entities(self) -> int:
        return self.padded_entities // self.num_shards

    @property
    def dense_trash_fraction(self) -> float:
        """Fraction of dense-stream walk slots that are trash (group /
        worst-chunk padding — empty [lo, hi) windows).  Measured 0.113 at
        the flagship full-Netflix 64k config; the kernel walk's cost is
        per-slot, so this bounds the recoverable walk time (VERDICT r4
        #6 — see BASELINE.md round-5 for why the residual is kept)."""
        if self.mode != "dstream" or self.num_tiles == 0:
            return 0.0
        ng, nt = self.num_groups, self.num_tiles
        tm = self.tile_meta.reshape(-1, ng + 4 * nt)
        lo = tm[:, ng + nt:ng + 2 * nt]
        hi = tm[:, ng + 2 * nt:ng + 3 * nt]
        return float(1.0 - (hi > lo).mean())

    @property
    def statics(self):
        """Static-shape tuple for the solve kernels: stream (NC, C, Ec, T),
        dstream (NC, C, Ec, T, NT, NG, BG), accum (NC, C, T, H, Ec)."""
        if self.mode == "stream":
            return (self.num_chunks, self.chunk_cap, self.chunk_entities,
                    self.tile_rows)
        if self.mode == "dstream":
            return (self.num_chunks, self.chunk_cap, self.chunk_entities,
                    self.tile_rows, self.num_tiles, self.num_groups,
                    self.block_rows)
        return (self.num_chunks, self.chunk_cap, self.tile_rows,
                self.slice_rows, self.chunk_entities)


TILED_SLICE_ROWS_DEFAULT = 1 << 17  # ≤34 MB bf16 rank-64 slice: the
# measured fast-gather regime (BASELINE.md); perf_lab keys caches on
# deviations from this same constant


def build_tiled_blocks(
    solve_dense: np.ndarray,
    fixed_dense: np.ndarray,
    rating: np.ndarray,
    num_solve_entities: int,
    num_fixed_entities: int,
    *,
    num_shards: int = 1,
    tile_rows: int = 128,
    chunk_elems: int | None = 1 << 20,
    slice_rows: int = TILED_SLICE_ROWS_DEFAULT,
    accum_max_entities: int = 1 << 16,
    ring: bool = False,
    dense_stream: bool = False,
) -> TiledBlocks:
    """Pad entity runs to tiles and pack into chunks (one mode per side).

    Mode selection: ``accum`` when the per-shard solve-entity count fits
    ``accum_max_entities`` (the [E+1, k, k] accumulator must fit in HBM),
    else ``stream``.  Table slicing engages only in accum mode and only
    when the padded fixed side exceeds ``slice_rows``.  ``dense_stream``
    upgrades the stream side to the unpadded dense layout
    (``_build_dense_stream`` — the measured explicit-ALS default at
    scale; iALS runs it too via the weighted channels, but measured
    slower than the padded stream at the ML-25M rank-128 target, see
    BASELINE.md round-4 notes).
    """
    if dense_stream and not ring:
        e_l = _round_up(num_solve_entities, num_shards) // num_shards
        if e_l > accum_max_entities:  # the side that would go stream mode
            return _build_dense_stream(
                solve_dense, fixed_dense, rating,
                num_solve_entities, num_fixed_entities,
                num_shards=num_shards, tile_rows=tile_rows,
                chunk_elems=chunk_elems,
            )
    t = int(tile_rows)
    if t < 8:
        raise ValueError(f"tile_rows must be >= 8, got {t}")
    e_pad = _round_up(num_solve_entities, num_shards)
    e_local = e_pad // num_shards
    f_pad = _round_up(num_fixed_entities, num_shards)
    if ring and e_local > accum_max_entities:
        # The ring join forces accum machinery: an [E_local+1, k, k+1]
        # accumulator per device.  Past accum_max_entities that
        # accumulator dwarfs the all_gather table the ring would save
        # (full Netflix user half: ~1 GB accumulator vs a 61 MB table) —
        # all_gather is strictly better there, so refuse instead of
        # building a memory trap.  ring="auto" picks per side.
        raise ValueError(
            f"ring=True with {e_local} solve entities per shard (> "
            f"accum_max_entities={accum_max_entities}): the ring's "
            "per-entity Gram accumulator would exceed the all_gather "
            "table it saves.  Use Dataset.from_coo(..., ring='auto') "
            "(ring only where it wins) or exchange='all_gather'."
        )
    if ring:
        # Ring (block-to-block join) exchange: slices ARE the fixed-side
        # factor shards, so at ring step r a device processes exactly the
        # sub-stream whose neighbors live in the block it currently holds.
        # Forces accum machinery: entities recur across slices, and the
        # per-entity accumulator [E_local+1, k, k+1] must fit HBM — the
        # ring's memory economics on TPU (see PARITY.md / BASELINE.md).
        mode = "accum"
        n_slices = num_shards
        # f_pad = _round_up(num_fixed, num_shards) above, so this divides.
        h = f_pad // num_shards
    else:
        mode = "accum" if e_local <= accum_max_entities else "stream"
        n_slices = 1
        h = f_pad
        if mode == "accum" and f_pad > slice_rows:
            h = int(slice_rows)
            n_slices = (f_pad + h - 1) // h

    order, count, _ = group_by_dense(solve_dense, num_solve_entities)
    s_sorted = solve_dense[order].astype(np.int64)
    f_sorted = fixed_dense[order].astype(np.int64)
    r_sorted = rating[order].astype(np.float32)
    local_sorted = (s_sorted % e_local).astype(np.int64)
    shard_of = s_sorted // e_local

    count_pad = np.zeros(e_pad, dtype=np.int32)
    count_pad[:num_solve_entities] = count
    rating_sum = np.zeros(e_pad, dtype=np.float32)
    rating_sum[:num_solve_entities] = np.bincount(
        solve_dense, weights=rating.astype(np.float64),
        minlength=num_solve_entities,
    ).astype(np.float32)

    cap = max(t, ((chunk_elems or (1 << 20)) // t) * t)
    nt = cap // t

    # Per-shard run construction (vectorized inside each shard).
    shard_data = []
    max_chunks = 1
    for s in range(num_shards):
        sel = shard_of == s
        loc = local_sorted[sel]
        fix = f_sorted[sel]
        rat = r_sorted[sel]
        # Within-run entry order is left as-is: sorting each run's entries
        # by neighbor index (Gram-invariant, free at build time) was
        # measured at full Netflix and changed NOTHING (0.710 vs 0.709
        # s/iter) — the gather engine is row-slot-bound and locality-
        # insensitive below its ~34 MB table cliff.
        if mode == "accum" and n_slices > 1:
            sl = fix // h
            o = np.lexsort((loc, sl))
            loc, fix, rat, sl = loc[o], fix[o], rat[o], sl[o]
        else:
            sl = np.zeros(loc.shape[0], dtype=np.int64)
        # Runs = consecutive equal (slice, entity) pairs; entries are sorted.
        if loc.shape[0]:
            key = sl * e_local + loc
            boundary = np.empty(loc.shape[0], dtype=bool)
            boundary[0] = True
            np.not_equal(key[1:], key[:-1], out=boundary[1:])
            run_start = np.flatnonzero(boundary)
            run_len = np.diff(np.append(run_start, loc.shape[0]))
            run_entity = loc[run_start]
            run_slice = sl[run_start]
        else:
            run_start = np.zeros(0, np.int64)
            run_len = np.zeros(0, np.int64)
            run_entity = np.zeros(0, np.int64)
            run_slice = np.zeros(0, np.int64)
        run_pad = ((run_len + t - 1) // t) * t
        slice_rounded = None
        if mode == "accum" and n_slices > 1:
            # Chunks must not span slices: pad each slice's stream to a
            # multiple of cap (slice_rounded is reused below to map chunks
            # back to their slice — one computation, one truth).
            padded_per_slice = np.bincount(
                run_slice, weights=run_pad.astype(np.float64),
                minlength=n_slices,
            ).astype(np.int64)
            slice_rounded = ((padded_per_slice + cap - 1) // cap) * cap
            slice_base = np.zeros(n_slices, dtype=np.int64)
            np.cumsum(slice_rounded[:-1], out=slice_base[1:])
            # Runs are slice-major (lexsort), so the within-slice offset is
            # the global exclusive cumsum minus the slice's first run's cum.
            cum = np.cumsum(run_pad) - run_pad
            first_idx = np.searchsorted(run_slice, np.arange(n_slices))
            valid = first_idx < run_slice.shape[0]
            base_correction = np.zeros(n_slices, dtype=np.int64)
            base_correction[valid] = cum[first_idx[valid]]
            run_dst = slice_base[run_slice] + (cum - base_correction[run_slice])
            total_padded = int(slice_rounded.sum())
        else:
            run_dst = np.cumsum(run_pad) - run_pad
            total_padded = int(run_pad.sum())
        nc_shard = max((total_padded + cap - 1) // cap, 1)
        max_chunks = max(max_chunks, nc_shard)
        shard_data.append(
            (loc, fix, rat, sl, run_start, run_len, run_entity, run_slice,
             run_pad, run_dst, total_padded, slice_rounded)
        )

    nc = max_chunks
    total = num_shards * nc * cap
    # Padding entries index the ZERO ROW the gram kernels append to the
    # fixed table/slice (= its height h), so gathered padding contributes
    # exact zeros even on the unit-weight fast path that never multiplies
    # by the weight channel.  (Format version 3 — older blocks pointed
    # padding at row 0 and relied on weight 0.)
    neighbor = np.full(total, h, dtype=np.int32)
    rmat = np.zeros(total, dtype=np.float32)
    wmat = np.zeros(total, dtype=np.float32)
    tile_seg = np.zeros(num_shards * nc * nt, dtype=np.int32)
    chunk_base = np.zeros(num_shards * nc, dtype=np.int32)
    carry_in = np.zeros(num_shards * nc, dtype=np.float32)
    last_seg = np.zeros(num_shards * nc, dtype=np.int32)

    # First pass: chunk entity spans → Ec (stream: solve-batch rows per
    # chunk; accum: accumulator window rows per chunk).
    e_c = 1
    tile_entity_by_shard = []
    for s in range(num_shards):
        (loc, fix, rat, sl, run_start, run_len, run_entity, run_slice,
         run_pad, run_dst, total_padded, slice_rounded) = shard_data[s]
        n_tiles_shard = nc * nt
        tile_entity = np.full(n_tiles_shard, e_local, dtype=np.int64)
        if run_len.shape[0]:
            tile_idx = run_dst // t
            reps = (run_pad // t).astype(np.int64)
            fill_pos = np.repeat(tile_idx, reps) + _concat_aranges(reps)
            tile_entity[fill_pos] = np.repeat(run_entity, reps)
        tile_entity_by_shard.append(tile_entity)
        te = tile_entity.reshape(nc, nt)
        for c in range(nc):
            real = te[c][te[c] < e_local]
            if real.size:
                if mode == "stream":  # solve-batch rows: entity SPAN
                    e_c = max(e_c, int(real[-1] - real[0]) + 1)
                else:  # accumulator scatter rows: DISTINCT entities
                    e_c = max(e_c, int(np.unique(real).shape[0]))
    e_c = min(e_c, e_local)

    chunk_entity = np.full(num_shards * nc * e_c, e_local, dtype=np.int32)
    chunk_count = np.zeros(num_shards * nc * e_c, dtype=np.int32)
    slice_starts = np.zeros(num_shards * (n_slices + 1), dtype=np.int32)

    for s in range(num_shards):
        (loc, fix, rat, sl, run_start, run_len, run_entity, run_slice,
         run_pad, run_dst, total_padded, slice_rounded) = shard_data[s]
        base = s * nc * cap
        if run_len.shape[0]:
            # Scatter real entries to their padded destinations.
            pos_in_run = np.arange(loc.shape[0], dtype=np.int64) - np.repeat(
                run_start, run_len
            )
            dst = base + np.repeat(run_dst, run_len) + pos_in_run
            if mode == "accum" and n_slices > 1:
                slice_first_row = np.minimum(sl * h, f_pad - h)
                neighbor[dst] = (fix - slice_first_row).astype(np.int32)
            else:
                neighbor[dst] = fix.astype(np.int32)
            rmat[dst] = rat
            wmat[dst] = 1.0

        tile_entity = tile_entity_by_shard[s]
        tbase = s * nc * nt
        if mode == "accum":
            te = tile_entity.reshape(nc, nt)
            for c in range(nc):
                ci = s * nc + c
                tiles_c = te[c]
                real = tiles_c < e_local
                if not real.any():
                    tile_seg[tbase + c * nt : tbase + (c + 1) * nt] = e_c
                    continue
                # Chunk-DENSE ranks: slicing leaves gaps in the entity
                # sequence, so ranks (not offsets) + an explicit entity
                # list; rank rows owning no tile route to the trash row.
                distinct = np.unique(tiles_c[real])
                seg = np.where(
                    real, np.searchsorted(distinct, tiles_c), e_c
                ).astype(np.int32)
                tile_seg[tbase + c * nt : tbase + (c + 1) * nt] = seg
                ebase = ci * e_c
                chunk_entity[ebase : ebase + distinct.shape[0]] = (
                    distinct.astype(np.int32)
                )
            sbase = s * (n_slices + 1)
            if n_slices > 1 and run_len.shape[0]:
                # chunk → slice: every chunk inside slice i's rounded span
                # (slice_rounded from the placement pass — same truth).
                chunks_per_slice = slice_rounded // cap
                sl_of_chunk = np.repeat(np.arange(n_slices), chunks_per_slice)
                cb = np.zeros(nc, dtype=np.int32)
                cb[: sl_of_chunk.shape[0]] = np.minimum(
                    sl_of_chunk * h, f_pad - h
                ).astype(np.int32)
                chunk_base[s * nc : (s + 1) * nc] = cb
                np.cumsum(
                    chunks_per_slice,
                    out=slice_starts[sbase + 1 : sbase + n_slices + 1],
                )
            else:
                slice_starts[sbase + 1 : sbase + n_slices + 1] = (
                    (total_padded + cap - 1) // cap
                )
            continue

        # Stream mode: chunk-relative segs + finalization bookkeeping.
        te = tile_entity.reshape(nc, nt)
        counts_local = count_pad.reshape(num_shards, e_local)[s]
        for c in range(nc):
            tiles_c = te[c]
            real = tiles_c < e_local
            ci = s * nc + c
            if not real.any():
                tile_seg[tbase + c * nt : tbase + (c + 1) * nt] = e_c
                continue
            first = int(tiles_c[real][0])
            last = int(tiles_c[real][-1])
            seg = np.where(real, tiles_c - first, e_c).astype(np.int32)
            tile_seg[tbase + c * nt : tbase + (c + 1) * nt] = seg
            carry_in[ci] = float(
                c > 0 and te[c - 1][te[c - 1] < e_local].size > 0
                and int(te[c - 1][te[c - 1] < e_local][-1]) == first
            )
            last_seg[ci] = last - first
            cont_out = c + 1 < nc and bool(
                (te[c + 1] < e_local).any()
                and int(te[c + 1][te[c + 1] < e_local][0]) == last
            )
            n_final = (last - first + 1) - int(cont_out)
            if n_final > 0:
                ebase = ci * e_c
                chunk_entity[ebase : ebase + n_final] = np.arange(
                    first, first + n_final, dtype=np.int32
                )
                chunk_count[ebase : ebase + n_final] = counts_local[
                    first : first + n_final
                ]

    return TiledBlocks(
        neighbor_idx=neighbor,
        rating=rmat,
        weight=wmat,
        tile_seg=tile_seg,
        chunk_base=chunk_base,
        chunk_entity=chunk_entity,
        chunk_count=chunk_count,
        carry_in=carry_in,
        last_seg=last_seg,
        slice_starts=slice_starts,
        count=count_pad,
        rating_sum=rating_sum,
        mode=mode,
        num_entities=num_solve_entities,
        num_shards=num_shards,
        num_chunks=nc,
        chunk_cap=cap,
        chunk_entities=e_c,
        tile_rows=t,
        slice_rows=h,
        num_slices=n_slices,
        ring=ring,
    )


DENSE_STREAM_BLOCK_ROWS = 1 << 15  # BG: gather-stream rows per pipelined
# kernel block.  Mosaic budgets bf16 VMEM windows at 4 B/elem (measured in
# the compile-OOM dump), so two 32k-row rank-64 blocks in flight cost
# ~17 MB next to the ~94 MB resident (A, b) output at full-Netflix Ec.
DENSE_STREAM_GROUP_TILES = 64  # M: tile slots per kernel grid step
DENSE_STREAM_ALIGN = 16  # run padding granularity = the bf16 (16, 128)
# VMEM tile height: 16-aligned window offsets land on whole sublane tiles,
# so the kernel's dynamic loads never straddle two tiles (8-aligned loads
# measured the whole dense win away); still only ~3.4%% padded slots at
# Netflix shape vs 26%% for full tile padding


def _balanced_entity_order(l8: np.ndarray, n_bins: int) -> np.ndarray:
    """Order entity indices so every stream window mixes long and short runs.

    Dense packing (no per-run tile padding) means a window of C rows holds
    C / mean(run length in the window) entities — regions of short runs
    pack several times more entities (and tiles) per chunk than the
    average, and the chunk-uniform statics (Ec, NT) are sized by the WORST
    chunk: an unbalanced order blows the kernel's resident (A, b) output
    past VMEM.  LPT bin packing: entities sorted by length are assigned
    greedily to the currently least-loaded of ``n_bins ≈ num_chunks``
    bins (longest-processing-time-first, the classic makespan heuristic)
    and the stream reads bins sequentially: per-bin row sums land within
    one entity of each other, and because similar-length entities place
    round-robin, per-bin entity (and tile) counts even out too — the
    chunk-uniform statics (Ec, NT) track the MEAN chunk instead of the
    worst.  (Tried and rejected at full Netflix: a two-pointer
    longest/shortest greedy — its pointers meet at the MEDIAN length,
    leaving an all-median tail with 1.6× the mean entity density; a
    snake round-robin deal — the Zipf head skews early bins, +14% Ec.)
    Solve order is free: entities are independent solves and
    ``chunk_entity`` carries explicit rows."""
    import heapq

    o = np.argsort(-l8, kind="stable")
    n = o.shape[0]
    nb = max(1, min(int(n_bins), n))
    if nb == 1:
        return o
    heap = [(0, j) for j in range(nb)]
    bins: list[list[int]] = [[] for _ in range(nb)]
    for e in o:
        rows, j = heapq.heappop(heap)
        bins[j].append(int(e))
        heapq.heappush(heap, (rows + int(l8[e]), j))
    return np.concatenate(
        [np.asarray(b, dtype=np.int64) for b in bins if b]
    )


def _build_dense_stream(
    solve_dense: np.ndarray,
    fixed_dense: np.ndarray,
    rating: np.ndarray,
    num_solve_entities: int,
    num_fixed_entities: int,
    *,
    num_shards: int = 1,
    tile_rows: int = 128,
    chunk_elems: int | None = 1 << 19,
    group_tiles: int = DENSE_STREAM_GROUP_TILES,
    block_rows: int = DENSE_STREAM_BLOCK_ROWS,
) -> TiledBlocks:
    """Dense-stream tiled blocks: tile structure WITHOUT tile padding.

    The padded stream layout (``mode="stream"``) rounds every entity's run
    up to a multiple of T gather slots — measured 26% wasted rows on the
    full-Netflix user half, directly on the binding resource (XLA's row
    gather engine is row-slot-bound at ~600M rows/s, BASELINE.md).  Here
    runs are padded only to 16 rows (bf16 sublane-tile alignment,
    ~3.4%), packed
    back-to-back, and tiles become [T]-row WINDOWS into the dense stream:
    per tile the kernel loads rows [lb, lb+T) at a dynamic 16-aligned
    offset and masks rows outside [lo, hi) — see
    ``ops.pallas.gram_kernel.gram_tiles_dense_pallas``.  The kernel
    pipelines the gathered stream in [BG, k] blocks selected by a
    scalar-prefetched per-group block index, so tiles never cross a BG
    boundary (the builder splits them there — same owner, and the walk
    accumulates same-owner tiles, so a split costs one extra slot).

    Per-tile metadata rides in ``tile_meta`` = [g_blk (NG) ‖ lb ‖ lo ‖
    hi ‖ seg (NT each)] per chunk.  Trash slots (group padding) INHERIT
    the previous real tile's seg with an empty [lo, hi) window, keeping
    every owner's tiles contiguous in the walk — the kernel contract.
    The b-side coefficients stay TILE-ALIGNED in ``rating`` ([NC·NT·T],
    zeros outside each tile's window) so b needs no in-kernel mask and no
    dynamic lane slicing.  For the WEIGHTED path (iALS) the blocks also
    carry ``weight`` tile-aligned (1.0 at real entries — the generic mask
    channel the iALS coefficient transform needs) and ``rating_dense``
    aligned with the gather stream (the per-entry A-weight source: the
    half-step premultiplies gw = g·aw in XLA, and the kernel masks the gw
    operand of each tile Gram).  Unit-weight explicit ALS never uploads
    those two arrays.

    Reference semantics unchanged: same normal equations per entity
    (``processors/MFeatureCalculator.java:85-99``), asserted equal to the
    padded layouts by ``tests/test_tiled.py``.
    """
    t = int(tile_rows)
    a8 = DENSE_STREAM_ALIGN
    if t % a8 != 0 or t < a8:
        raise ValueError(
            f"dense stream needs tile_rows % {a8} == 0, got {t}"
        )
    cap = max(t, chunk_elems or (1 << 19))
    bg = int(block_rows)
    if bg < t:
        bg = ((t + a8 - 1) // a8) * a8
    if cap < bg:
        bg = ((cap + a8 - 1) // a8) * a8
        cap = bg
    else:
        cap = (cap // bg) * bg  # chunk boundaries are block boundaries
    m = int(group_tiles)
    e_pad = _round_up(num_solve_entities, num_shards)
    e_local = e_pad // num_shards
    f_pad = _round_up(num_fixed_entities, num_shards)
    h = f_pad  # padding entries index the appended zero row

    order, count, _ = group_by_dense(solve_dense, num_solve_entities)
    s_sorted = solve_dense[order].astype(np.int64)
    f_sorted = fixed_dense[order].astype(np.int64)
    r_sorted = rating[order].astype(np.float32)
    local_sorted = (s_sorted % e_local).astype(np.int64)
    shard_of = s_sorted // e_local

    count_pad = np.zeros(e_pad, dtype=np.int32)
    count_pad[:num_solve_entities] = count
    rating_sum = np.zeros(e_pad, dtype=np.float32)
    rating_sum[:num_solve_entities] = np.bincount(
        solve_dense, weights=rating.astype(np.float64),
        minlength=num_solve_entities,
    ).astype(np.float32)

    shards = []
    nc_max, ng_max, ec_max = 1, 1, 1
    for s in range(num_shards):
        sel = shard_of == s
        loc = local_sorted[sel]
        fix = f_sorted[sel]
        rat = r_sorted[sel]
        counts_local = count_pad.reshape(num_shards, e_local)[s]
        if loc.shape[0] == 0:
            shards.append(None)
            continue
        l_all = np.bincount(loc, minlength=e_local).astype(np.int64)
        present = np.flatnonzero(l_all)
        lp = l_all[present]
        l8 = (lp + DENSE_STREAM_ALIGN - 1) // DENSE_STREAM_ALIGN * DENSE_STREAM_ALIGN
        perm = _balanced_entity_order(
            l8, (int(l8.sum()) + cap - 1) // cap
        )
        n = present.shape[0]
        rank_full = np.full(e_local, -1, dtype=np.int64)
        rank_full[present[perm]] = np.arange(n)
        ord2 = np.argsort(rank_full[loc], kind="stable")
        fix2 = fix[ord2]
        rat2 = rat[ord2]
        l_in = lp[perm]
        l8_in = l8[perm]
        run_start8 = np.cumsum(l8_in) - l8_in
        total8 = int(l8_in.sum())
        pos_in_run = _concat_aranges(l_in)
        dst = run_start8[np.repeat(np.arange(n), l_in)] + pos_in_run

        nc_shard = max((total8 + cap - 1) // cap, 1)
        # Tiles: pieces between (run start ∪ BG-boundary) cuts, then T-cut.
        bg_cuts = np.arange(bg, total8, bg, dtype=np.int64)
        cuts = np.union1d(run_start8, bg_cuts)
        piece_start = cuts
        piece_end = np.append(cuts[1:], total8)
        piece_run = np.searchsorted(run_start8, piece_start, side="right") - 1
        tpp = (piece_end - piece_start + t - 1) // t
        tile_off = np.repeat(piece_start, tpp) + _concat_aranges(tpp) * t
        tile_end = np.minimum(tile_off + t, np.repeat(piece_end, tpp))
        tile_run = np.repeat(piece_run, tpp)
        ntile = tile_off.shape[0]
        tile_chunk = tile_off // cap
        nbc = cap // bg
        tile_blk_abs = tile_off // bg
        blk_in_chunk = (tile_blk_abs - tile_chunk * nbc).astype(np.int64)
        off_rel = tile_off - tile_blk_abs * bg
        lb = np.minimum(off_rel, bg - t)
        lo = off_rel - lb
        hi = lo + (tile_end - tile_off)

        cft = np.searchsorted(tile_chunk, np.arange(nc_shard), side="left")
        clt = np.searchsorted(tile_chunk, np.arange(nc_shard), side="right") - 1
        first_rank = tile_run[cft]
        last_rank = tile_run[clt]
        seg_val = tile_run - first_rank[tile_chunk]
        span = last_rank - first_rank + 1

        # Groups: ≤ m consecutive tiles sharing one (chunk, block).
        key = tile_chunk * nbc + blk_in_chunk
        key_change = np.empty(ntile, dtype=bool)
        key_change[0] = True
        np.not_equal(key[1:], key[:-1], out=key_change[1:])
        key_start = np.flatnonzero(key_change)
        idx_in_key = (
            np.arange(ntile) - key_start[np.cumsum(key_change) - 1]
        )
        g_change = key_change | (idx_in_key % m == 0)
        g_id = np.cumsum(g_change) - 1
        g_in_chunk = g_id - g_id[cft][tile_chunk]
        slot = g_in_chunk * m + idx_in_key % m
        ng_shard = int(g_in_chunk[clt].max()) + 1

        nc_max = max(nc_max, nc_shard)
        ng_max = max(ng_max, ng_shard)
        ec_max = max(ec_max, int(span.max()))
        shards.append(dict(
            fix2=fix2, rat2=rat2, dst=dst, total8=total8,
            nc_shard=nc_shard, present=present, perm=perm,
            l_all=l_all, tile_off=tile_off, tile_chunk=tile_chunk,
            blk_in_chunk=blk_in_chunk, lb=lb, lo=lo, hi=hi,
            seg_val=seg_val, g_change=g_change, g_in_chunk=g_in_chunk,
            slot=slot, first_rank=first_rank, last_rank=last_rank,
            span=span, counts_local=counts_local,
        ))

    nc, ng = nc_max, ng_max
    nt = ng * m
    e_c = min(ec_max, e_local)
    mw = ng + 4 * nt
    neighbor = np.full(num_shards * nc * cap, h, dtype=np.int32)
    rt_tiled = np.zeros(num_shards * nc * nt * t, dtype=np.float32)
    wt_tiled = np.zeros(num_shards * nc * nt * t, dtype=np.float32)
    rating_dense = np.zeros(num_shards * nc * cap, dtype=np.float32)
    tile_meta = np.zeros((num_shards, nc, mw), dtype=np.int32)
    chunk_entity = np.full(num_shards * nc * e_c, e_local, dtype=np.int32)
    chunk_count = np.zeros(num_shards * nc * e_c, dtype=np.int32)
    carry_in = np.zeros(num_shards * nc, dtype=np.float32)
    last_seg = np.zeros(num_shards * nc, dtype=np.int32)

    for s in range(num_shards):
        d = shards[s]
        if d is None:
            tile_meta[s, :, ng + 3 * nt:] = e_c  # all-trash seg
            continue
        base = s * nc * cap
        neighbor[base + d["dst"]] = d["fix2"].astype(np.int32)
        rating_dense[base + d["dst"]] = d["rat2"]

        tc, sl = d["tile_chunk"], d["slot"]
        lbv, lov, hiv, sgv = d["lb"], d["lo"], d["hi"], d["seg_val"]
        # Entries → tile-aligned rating/weight slots.
        et = np.searchsorted(d["tile_off"], d["dst"], side="right") - 1
        row = d["dst"] - d["tile_off"][et] + lov[et]
        rt_idx = (s * nc + tc[et]) * nt * t + sl[et] * t + row
        rt_tiled[rt_idx] = d["rat2"]
        wt_tiled[rt_idx] = 1.0

        meta = tile_meta[s]
        gsel = d["g_change"]
        meta[tc[gsel], d["g_in_chunk"][gsel]] = d["blk_in_chunk"][gsel]
        flat = np.full((nc, nt), -1, dtype=np.int64)
        flat[tc, sl] = np.arange(tc.shape[0])
        filled = flat >= 0
        src = np.where(filled, flat, 0)
        meta[:, ng:ng + nt] = np.where(filled, lbv[src], 0)
        meta[:, ng + nt:ng + 2 * nt] = np.where(filled, lov[src], 0)
        meta[:, ng + 2 * nt:ng + 3 * nt] = np.where(filled, hiv[src], 0)
        # hi == lo marks trash; seg forward-fills from the previous real
        # tile so every owner's tiles stay contiguous in the walk (leading
        # trash in an all-trash chunk falls through to e_c).
        seg_slots = np.where(filled, sgv[src], -1)
        ffill = np.where(filled, np.arange(nt)[None, :], 0)
        np.maximum.accumulate(ffill, axis=1, out=ffill)
        seg_f = np.take_along_axis(seg_slots, ffill, axis=1)
        any_before = np.maximum.accumulate(filled, axis=1)
        meta[:, ng + 3 * nt:] = np.where(any_before, seg_f, e_c)

        fr, lr, spn = d["first_rank"], d["last_rank"], d["span"]
        nc_shard = d["nc_shard"]
        rows_of_rank = d["present"][d["perm"]]
        counts_local = d["counts_local"]
        for c in range(nc_shard):
            ci = s * nc + c
            carry_in[ci] = float(c > 0 and lr[c - 1] == fr[c])
            last_seg[ci] = spn[c] - 1
            cont_out = c + 1 < nc_shard and fr[c + 1] == lr[c]
            n_final = int(spn[c]) - int(cont_out)
            if n_final > 0:
                ebase = ci * e_c
                rows = rows_of_rank[fr[c]:fr[c] + n_final]
                chunk_entity[ebase:ebase + n_final] = rows.astype(np.int32)
                chunk_count[ebase:ebase + n_final] = counts_local[rows]
        tile_meta[s, nc_shard:, ng + 3 * nt:] = e_c

    return TiledBlocks(
        neighbor_idx=neighbor,
        rating=rt_tiled,
        weight=wt_tiled,
        tile_seg=np.zeros(0, dtype=np.int32),
        chunk_base=np.zeros(0, dtype=np.int32),
        chunk_entity=chunk_entity,
        chunk_count=chunk_count,
        carry_in=carry_in,
        last_seg=last_seg,
        slice_starts=np.zeros(0, dtype=np.int32),
        count=count_pad,
        rating_sum=rating_sum,
        mode="dstream",
        num_entities=num_solve_entities,
        num_shards=num_shards,
        num_chunks=nc,
        chunk_cap=cap,
        chunk_entities=e_c,
        tile_rows=t,
        slice_rows=h,
        num_slices=1,
        ring=False,
        tile_meta=tile_meta.reshape(-1),
        rating_dense=rating_dense,
        num_tiles=nt,
        num_groups=ng,
        block_rows=bg,
    )


def _concat_aranges(lengths: np.ndarray) -> np.ndarray:
    """[0..l0), [0..l1), ... concatenated — vectorized."""
    if lengths.shape[0] == 0:
        return np.zeros(0, dtype=np.int64)
    total = int(lengths.sum())
    out = np.arange(total, dtype=np.int64)
    starts = np.cumsum(lengths) - lengths
    return out - np.repeat(starts, lengths)


@dataclasses.dataclass(frozen=True)
class RatingsIndex:
    """Id maps + dense-index COO without any solve-block build.

    The cheap subset of ``Dataset`` that serving needs (raw↔dense id mapping
    and exclude-seen lists): parsing + two sorts, no rectangles — so a
    full-Netflix ``recommend`` never pays the training-layout memory.
    """

    movie_map: IdMap
    user_map: IdMap
    coo_dense: RatingsCOO

    @classmethod
    def from_coo(cls, coo: RatingsCOO) -> "RatingsIndex":
        movie_map, m_dense = index_entities(coo.movie_raw)
        user_map, u_dense = index_entities(coo.user_raw)
        return cls(
            movie_map=movie_map,
            user_map=user_map,
            coo_dense=RatingsCOO(
                movie_raw=m_dense.astype(np.int64),
                user_raw=u_dense.astype(np.int64),
                rating=coo.rating.astype(np.float32),
            ),
        )


@dataclasses.dataclass(frozen=True)
class Dataset:
    """A fully indexed rating dataset: id maps + both solve-side block sets.

    ``layout="padded"`` builds one rectangle per side (fine up to medium-scale
    data); ``layout="bucketed"`` builds power-of-two width classes — required
    at full-Netflix scale where the max-degree entity would blow up the single
    rectangle; ``layout="segment"`` keeps ratings flat CSR-style and
    accumulates Gram matrices by segment_sum — exactly O(nnz) memory for
    arbitrarily skewed degree distributions.
    """

    movie_map: IdMap
    user_map: IdMap
    movie_blocks: "PaddedBlocks | BucketedBlocks | SegmentBlocks | TiledBlocks"  # solve movies, neighbors are users
    user_blocks: "PaddedBlocks | BucketedBlocks | SegmentBlocks | TiledBlocks"  # solve users, neighbors are movies
    coo_dense: RatingsCOO  # dense-index COO (movie_raw/user_raw hold dense idx)

    def save(self, path: str, build_key: dict | None = None) -> None:
        """Cache the built dataset on disk; see ``cfk_tpu.data.cache``."""
        from cfk_tpu.data.cache import save_dataset

        save_dataset(self, path, build_key=build_key)

    @classmethod
    def load(cls, path: str, expect_build_key: dict | None = None) -> "Dataset":
        """Load a dataset cached with ``save``."""
        from cfk_tpu.data.cache import load_dataset

        return load_dataset(path, expect_build_key=expect_build_key)

    @classmethod
    def from_coo(
        cls,
        coo: RatingsCOO,
        *,
        num_shards: int = 1,
        pad_multiple: int = 8,
        layout: str = "padded",
        chunk_elems: int | None = 1 << 20,
        ring: bool | str | tuple = False,
        accum_max_entities: int = 1 << 16,
        rank_hint: int = 64,
        dense_stream: bool = False,
        ring_warn: bool = True,
        tile_rows: int = 128,
    ) -> "Dataset":
        """``ring`` (tiled layout): False/True build both halves for the
        all_gather/ring exchange; a ``(movie_ring, user_ring)`` tuple sets
        each half explicitly; ``"auto"`` picks PER HALF by the actual
        memory comparison — ring exactly where its per-device bytes
        (fixed-table shard + the [E_local+1, k, k+1] Gram accumulator)
        undercut the all_gather'd full table, evaluated at ``rank_hint``
        (bf16 factors assumed — the at-scale default; f32 only favors
        ring more).  At Netflix shape that is ring movie-half (rotate
        480k-user blocks instead of all_gathering 61 MB) + all_gather
        user-half (whose ring accumulator would be ~1 GB), the optimum
        the exchange comparison identifies (BASELINE.md).

        ``dense_stream`` (tiled layout) upgrades each STREAM-mode half to
        the unpadded dense layout; a half that runs in accum mode (its
        per-shard solve entities fit ``accum_max_entities`` — e.g. the
        movie half at Netflix shape) keeps the accum layout by design, and
        ring halves carry the accum machinery too, so ``ring=True`` +
        ``dense_stream=True`` leaves no half for the flag and warns."""
        movie_map, m_dense = index_entities(coo.movie_raw)
        user_map, u_dense = index_entities(coo.user_raw)
        if layout == "bucketed":
            build = functools.partial(
                build_bucketed_blocks,
                num_shards=num_shards,
                pad_multiple=pad_multiple,
                chunk_elems=chunk_elems,
            )
        elif layout == "segment":
            # chunk_elems budgets gather cells·k, same as the rectangular
            # layouts: the ragged-matmul Gram backend's peak per chunk is the
            # [C, k] gather.  A JAX without ragged_dot_general falls back to
            # segment_sum, whose peak is the [C, k, k] outer-product tensor —
            # shrink the chunk by a worst-case rank so the same flag keeps
            # meaning "HBM budget" there too.
            from cfk_tpu.ops.solve import default_segment_backend

            chunk_nnz = chunk_elems
            if chunk_nnz is not None and default_segment_backend() == "segsum":
                chunk_nnz = max(64, chunk_nnz // 64)
            build = functools.partial(
                build_segment_blocks,
                num_shards=num_shards,
                pad_multiple=pad_multiple,
                chunk_nnz=chunk_nnz,
            )
        elif layout == "tiled":
            build = functools.partial(
                build_tiled_blocks,
                num_shards=num_shards,
                chunk_elems=chunk_elems,
                accum_max_entities=accum_max_entities,
                dense_stream=dense_stream,
                tile_rows=tile_rows,
            )
        elif layout == "padded":
            build = functools.partial(
                build_padded_blocks, num_shards=num_shards, pad_multiple=pad_multiple
            )
        else:
            raise ValueError(f"unknown layout {layout!r}")
        if ring and layout != "tiled":
            raise ValueError(
                "ring applies to layout='tiled' (the padded layout's "
                "ring blocks are built by the sharded trainer itself)"
            )
        if dense_stream and layout != "tiled":
            raise ValueError("dense_stream applies to layout='tiled'")
        if not isinstance(ring, (bool, tuple)) and ring != "auto":
            raise ValueError(
                f"ring must be True/False/'auto'/(movie, user), got {ring!r}"
            )
        if layout == "tiled":
            def ring_saves_memory(n_solve: int, n_fixed: int) -> bool:
                # Per-device bytes, bf16 factors at rank_hint: ring holds
                # one fixed-table shard plus the per-entity accumulator;
                # all_gather holds the whole fixed table.
                k = rank_hint
                e_local = -(-n_solve // num_shards)
                f_pad = _round_up(n_fixed, num_shards)
                acc = (e_local + 1) * (k * k + k) * 4
                return f_pad // num_shards * k * 2 + acc < f_pad * k * 2

            def fits_accum(n_solve: int) -> bool:
                # The ring forces accum machinery; past the cap the
                # builder refuses outright (build_tiled_blocks).
                return -(-n_solve // num_shards) <= accum_max_entities

            if ring == "auto":
                m_ring = (ring_saves_memory(movie_map.num_entities,
                                            user_map.num_entities)
                          and fits_accum(movie_map.num_entities))
                u_ring = (ring_saves_memory(user_map.num_entities,
                                            movie_map.num_entities)
                          and fits_accum(user_map.num_entities))
            else:
                if isinstance(ring, tuple):
                    m_ring, u_ring = ring
                else:
                    m_ring = u_ring = ring
                # ``ring_warn=False`` is the deliberate-measurement opt-out
                # (A/B runs, dryrun_multichip's tiny-shape ring builds) so
                # recorded artifacts stay clean and a REAL memory warning
                # remains visible when it matters.
                for side, r, ns, nf in (
                    ("movie", m_ring, movie_map.num_entities,
                     user_map.num_entities),
                    ("user", u_ring, user_map.num_entities,
                     movie_map.num_entities),
                ):
                    if (ring_warn and r and fits_accum(ns)
                            and not ring_saves_memory(ns, nf)):
                        import warnings

                        warnings.warn(
                            f"ring-built {side} half: the per-entity Gram "
                            "accumulator exceeds the all_gather table it "
                            f"saves (at rank≈{rank_hint}) — all_gather is "
                            "strictly better there; consider ring='auto'",
                            stacklevel=2,
                        )
            if dense_stream and m_ring and u_ring and ring_warn \
                    and ring != "auto":
                # Ring halves carry the accum machinery (per-slice sweeps
                # need the per-entity accumulator), so with BOTH resolved
                # halves ring-built the dense-stream request has no half to
                # apply to — warn instead of silently dropping it
                # (ADVICE r4); the per-half accum fallback is documented in
                # the docstring above.  ring='auto' is exempt: there the
                # ring resolution is the requested memory optimum, not a
                # user error the warning could correct.
                import warnings

                warnings.warn(
                    "dense_stream=True is ignored: both halves are "
                    "ring-built (ring implies the accum machinery); build "
                    "with ring=False/'auto' or drop dense_stream",
                    stacklevel=2,
                )
            movie_blocks = build(
                m_dense, u_dense, coo.rating,
                movie_map.num_entities, user_map.num_entities, ring=m_ring,
            )
            user_blocks = build(
                u_dense, m_dense, coo.rating,
                user_map.num_entities, movie_map.num_entities, ring=u_ring,
            )
        else:
            movie_blocks = build(m_dense, u_dense, coo.rating, movie_map.num_entities)
            user_blocks = build(u_dense, m_dense, coo.rating, user_map.num_entities)
        return cls(
            movie_map=movie_map,
            user_map=user_map,
            movie_blocks=movie_blocks,
            user_blocks=user_blocks,
            coo_dense=RatingsCOO(
                movie_raw=m_dense.astype(np.int64),
                user_raw=u_dense.astype(np.int64),
                rating=coo.rating.astype(np.float32),
            ),
        )
