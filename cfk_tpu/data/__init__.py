from cfk_tpu.data.netflix import parse_netflix
from cfk_tpu.data.blocks import IdMap, RatingsCOO, PaddedBlocks, build_padded_blocks

__all__ = ["parse_netflix", "IdMap", "RatingsCOO", "PaddedBlocks", "build_padded_blocks"]
