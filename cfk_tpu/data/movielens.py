"""MovieLens CSV ingest (ml-25m ``ratings.csv`` format).

Grammar: optional header ``userId,movieId,rating,timestamp``, then rows
``userId,movieId,rating,timestamp``.  Timestamps are ignored (like the
reference ignores Netflix dates).  For the implicit-feedback pipeline the
rating column is treated as interaction strength; ``min_rating`` lets the
caller binarize/threshold (a common MovieLens-implicit protocol).
"""

from __future__ import annotations

import re

import numpy as np

# Plain non-negative decimal (digits, optional .digits) — what the native
# parser's bounded float reader accepts; no sign or scientific notation.
_RATING_RE = re.compile(r"\d+(\.\d*)?|\.\d+")

_INT64_MAX = 2**63 - 1

from cfk_tpu.data.blocks import RatingsCOO


def parse_movielens_csv(path: str, *, min_rating: float = 0.0) -> RatingsCOO:
    try:
        from cfk_tpu.data import _native

        if _native.available():
            return _native.parse_movielens(path, min_rating)
    except ImportError:
        pass
    return parse_movielens_csv_python(path, min_rating=min_rating)


def parse_movielens_csv_python(path: str, *, min_rating: float = 0.0) -> RatingsCOO:
    users: list[int] = []
    movies: list[int] = []
    ratings: list[float] = []
    with open(path, "r") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            if lineno == 1 and line.lower().startswith("userid"):
                continue  # header
            parts = line.split(",")
            if len(parts) < 3:
                raise ValueError(f"{path}:{lineno}: malformed line {line!r}")
            try:
                # Strict non-negative ids (no sign/underscores) to match the
                # native parser exactly; ids feed mod-N partitioning, where a
                # negative id would collide with control-record conventions.
                if not (parts[0].isdigit() and parts[1].isdigit()):
                    raise ValueError("non-numeric id")
                if not _RATING_RE.fullmatch(parts[2]):
                    raise ValueError("malformed rating")
                user, movie, rating = int(parts[0]), int(parts[1]), float(parts[2])
                if user > _INT64_MAX or movie > _INT64_MAX:
                    raise ValueError("id exceeds int64")
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: malformed line {line!r}") from e
            if rating < min_rating:
                continue
            users.append(user)
            movies.append(movie)
            ratings.append(rating)
    return RatingsCOO(
        movie_raw=np.asarray(movies, dtype=np.int64),
        user_raw=np.asarray(users, dtype=np.int64),
        rating=np.asarray(ratings, dtype=np.float32),
    )
