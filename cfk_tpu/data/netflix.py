"""Netflix-Prize-format ingest.

Grammar (matching ``producers/NetflixDataFormatProducer.java:44-50``):

    <movieId>:            — header line, sets the current movie
    <userId>,<rating>,<date>   — one rating row; the date field is ignored
                                 (reference ignores it too, :48-50)

Movies with zero rating rows exist in the files (e.g. tiny has 1,000 headers
but only 426 rated movies) and are dropped — NUM_MOVIES/NUM_USERS in the
reference count *rated* entities only (see SURVEY.md §6 footnote).

A native C++ parser (``native/``) is used when its shared library has been
built; this pure-Python path is the always-available fallback and the
reference implementation for tests.
"""

from __future__ import annotations

import numpy as np

from cfk_tpu.data.blocks import RatingsCOO

_INT64_MAX = 2**63 - 1


def parse_netflix_python(path: str) -> RatingsCOO:
    """Pure-Python Netflix-format parser (fallback / reference)."""
    movie_ids: list[int] = []
    user_ids: list[int] = []
    ratings: list[int] = []
    current_movie = -1
    with open(path, "r") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                if line.endswith(":"):
                    # Strict digits (no sign/underscores) within int64,
                    # matching the native parser exactly.
                    if not line[:-1].isdigit():
                        raise ValueError("non-numeric movie id")
                    current_movie = int(line[:-1])
                    if current_movie > _INT64_MAX:
                        raise ValueError("movie id exceeds int64")
                    continue
                # userId,rating,date — date ignored
                user_s, rating_s, _ = line.split(",", 2)
                if not (user_s.isdigit() and rating_s.isdigit()):
                    raise ValueError("non-numeric field")
                user_id, rating = int(user_s), int(rating_s)
                if user_id > _INT64_MAX or rating > _INT64_MAX:
                    raise ValueError("field exceeds int64")
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: malformed line {line!r}") from e
            if current_movie < 0:
                raise ValueError(
                    f"{path}:{lineno}: rating row before any 'movieId:' header"
                )
            movie_ids.append(current_movie)
            user_ids.append(user_id)
            ratings.append(rating)
    return RatingsCOO(
        movie_raw=np.asarray(movie_ids, dtype=np.int64),
        user_raw=np.asarray(user_ids, dtype=np.int64),
        rating=np.asarray(ratings, dtype=np.float32),
    )


def parse_netflix(path: str) -> RatingsCOO:
    """Parse a Netflix-format ratings file into COO arrays.

    Uses the native C++ parser when available, else pure Python.
    """
    try:
        from cfk_tpu.data import _native

        if _native.available():
            return _native.parse_netflix(path)
    except ImportError:
        pass
    return parse_netflix_python(path)
