"""MSE / RMSE evaluation.

In-process equivalent of the reference's offline evaluator
(``scripts/calculate_mse.py:78-91``): mean squared error over the observed
(nonzero) rating cells only, against the dense prediction matrix whose rows
are users ascending by id and columns movies ascending by id.
"""

from __future__ import annotations

import math

import numpy as np

from cfk_tpu.data.blocks import Dataset


def mse_rmse(
    predictions: np.ndarray,  # [num_users, num_movies]
    user_dense: np.ndarray,  # [nnz] dense user indices
    movie_dense: np.ndarray,  # [nnz] dense movie indices
    rating: np.ndarray,  # [nnz]
) -> tuple[float, float]:
    """MSE/RMSE over observed ratings (vectorized; no dense ratings matrix)."""
    pred = predictions[user_dense, movie_dense]
    se = float(np.sum((rating.astype(np.float64) - pred.astype(np.float64)) ** 2))
    mse = se / rating.shape[0]
    return mse, math.sqrt(mse)


def mse_rmse_from_blocks(predictions: np.ndarray, dataset: Dataset) -> tuple[float, float]:
    return mse_rmse(
        predictions,
        dataset.coo_dense.user_raw,
        dataset.coo_dense.movie_raw,
        dataset.coo_dense.rating,
    )
