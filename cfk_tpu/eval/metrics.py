"""MSE / RMSE evaluation.

In-process equivalent of the reference's offline evaluator
(``scripts/calculate_mse.py:78-91``): mean squared error over the observed
(nonzero) rating cells only, against the dense prediction matrix whose rows
are users ascending by id and columns movies ascending by id.
"""

from __future__ import annotations

import math

import numpy as np

from cfk_tpu.data.blocks import Dataset


def mse_rmse(
    predictions: np.ndarray,  # [num_users, num_movies]
    user_dense: np.ndarray,  # [nnz] dense user indices
    movie_dense: np.ndarray,  # [nnz] dense movie indices
    rating: np.ndarray,  # [nnz]
) -> tuple[float, float]:
    """MSE/RMSE over observed ratings (vectorized; no dense ratings matrix)."""
    pred = predictions[user_dense, movie_dense]
    se = float(np.sum((rating.astype(np.float64) - pred.astype(np.float64)) ** 2))
    mse = se / rating.shape[0]
    return mse, math.sqrt(mse)


def mse_rmse_from_blocks(predictions: np.ndarray, dataset: Dataset) -> tuple[float, float]:
    return mse_rmse(
        predictions,
        dataset.coo_dense.user_raw,
        dataset.coo_dense.movie_raw,
        dataset.coo_dense.rating,
    )


def mse_rmse_heldout(
    model, dataset, held, chunk: int = 1 << 22
) -> tuple[float, float, int]:
    """(MSE, RMSE, cells evaluated) on held-out raw-id cells.

    ``held`` is a RatingsCOO with RAW external ids; cells whose user or
    movie never appeared in training (no dense index) are dropped — their
    factors don't exist.  Streams factor-space dot products like
    ``mse_rmse_from_model``.  Used by the planted-factor quality
    validation (bench.py --planted, tests/test_planted.py).
    """
    u, m = model.host_factors()
    um, mm = dataset.user_map, dataset.movie_map
    u_idx = np.searchsorted(um.raw_ids, held.user_raw)
    m_idx = np.searchsorted(mm.raw_ids, held.movie_raw)
    u_idx = np.minimum(u_idx, um.num_entities - 1)
    m_idx = np.minimum(m_idx, mm.num_entities - 1)
    known = (um.raw_ids[u_idx] == held.user_raw) & (
        mm.raw_ids[m_idx] == held.movie_raw
    )
    ud, md, r = u_idx[known], m_idx[known], held.rating[known]
    se = 0.0
    for lo in range(0, r.shape[0], chunk):
        sl = slice(lo, lo + chunk)
        pred = np.einsum("nk,nk->n", u[ud[sl]], m[md[sl]], dtype=np.float64)
        se += float(np.sum((r[sl].astype(np.float64) - pred) ** 2))
    n = int(r.shape[0])
    mse = se / max(n, 1)
    return mse, math.sqrt(mse), n


def mse_rmse_from_model(model, dataset: Dataset, chunk: int = 1 << 22) -> tuple[float, float]:
    """MSE/RMSE straight from the factor matrices, never materializing P.

    Predictions at the observed cells are per-row dot products
    ``Σ_k U[u,k]·M[m,k]`` streamed in nnz chunks — O(chunk·k) memory, so it
    works at full-Netflix scale where the dense U·Mᵀ matrix
    (``ALSModel.predict_dense``) would be hundreds of GB.
    """
    u, m = model.host_factors()
    ud = dataset.coo_dense.user_raw
    md = dataset.coo_dense.movie_raw
    r = dataset.coo_dense.rating
    se = 0.0
    for lo in range(0, r.shape[0], chunk):
        sl = slice(lo, lo + chunk)
        pred = np.einsum(
            "nk,nk->n", u[ud[sl]], m[md[sl]], dtype=np.float64
        )
        se += float(np.sum((r[sl].astype(np.float64) - pred) ** 2))
    mse = se / max(r.shape[0], 1)
    return mse, math.sqrt(mse)
