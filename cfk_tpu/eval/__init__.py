from cfk_tpu.eval.metrics import mse_rmse, mse_rmse_from_blocks
from cfk_tpu.eval.predict import save_prediction_csv, load_prediction_csv

__all__ = [
    "mse_rmse",
    "mse_rmse_from_blocks",
    "save_prediction_csv",
    "load_prediction_csv",
]
