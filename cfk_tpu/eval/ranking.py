"""Ranking evaluation for the implicit model: leave-one-out Recall@K / MPR.

The reference's only metric is observed-cell MSE (``scripts/calculate_mse.py``);
implicit feedback needs ranking metrics instead — each held-out item is
ranked among all items the user has NOT interacted with in training.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cfk_tpu.data.blocks import RatingsCOO


@dataclasses.dataclass(frozen=True)
class Heldout:
    user_dense: np.ndarray  # [n] dense user index
    movie_dense: np.ndarray  # [n] dense movie index of the held-out item


def leave_one_out_split(
    movie_dense: np.ndarray,
    user_dense: np.ndarray,
    rating: np.ndarray,
    *,
    seed: int = 0,
) -> tuple[RatingsCOO, Heldout]:
    """Hold out one random interaction per user with ≥ 2 interactions.

    Inputs are dense-index COO arrays; returns (train COO in dense indices,
    heldout).  Users with a single interaction keep it in train, and an
    interaction is only held out while its movie retains ≥ 2 interactions —
    so every entity stays covered in train and the dense index space of a
    Dataset built from ``train`` coincides with the full dataset's (holding
    out a movie's last interaction would silently shift all later movie
    indices and mis-align ranking evaluation).
    """
    rng = np.random.default_rng(seed)
    order = rng.permutation(user_dense.shape[0])
    held_mask = np.zeros(user_dense.shape[0], dtype=bool)
    user_counts = np.bincount(user_dense)
    movie_counts = np.bincount(movie_dense)
    seen: set[int] = set()
    for idx in order:
        u = int(user_dense[idx])
        mv = int(movie_dense[idx])
        if u not in seen and user_counts[u] >= 2 and movie_counts[mv] >= 2:
            held_mask[idx] = True
            seen.add(u)
            movie_counts[mv] -= 1
    train = RatingsCOO(
        movie_raw=movie_dense[~held_mask].astype(np.int64),
        user_raw=user_dense[~held_mask].astype(np.int64),
        rating=rating[~held_mask].astype(np.float32),
    )
    heldout = Heldout(
        user_dense=user_dense[held_mask].astype(np.int64),
        movie_dense=movie_dense[held_mask].astype(np.int64),
    )
    return train, heldout


def _ranks(
    scores: np.ndarray,  # [num_users, num_movies]
    train: RatingsCOO,  # dense-index COO of training interactions
    heldout: Heldout,
) -> np.ndarray:
    """0-based rank of each held-out item among that user's non-train items."""
    if train.user_raw.max(initial=-1) >= scores.shape[0] or train.movie_raw.max(
        initial=-1
    ) >= scores.shape[1]:
        raise ValueError(
            f"train indices exceed score matrix {scores.shape} — the model was "
            "trained on a dataset with a different dense index space than the "
            "split; build the split with leave_one_out_split so every entity "
            "stays covered in train"
        )
    s = scores.copy()
    s[train.user_raw, train.movie_raw] = -np.inf  # exclude seen items
    held_scores = s[heldout.user_dense, heldout.movie_dense]
    cand = s[heldout.user_dense]
    better = (cand > held_scores[:, None]).sum(axis=1)
    # Ties count half (excluding the held item's own cell) — otherwise a
    # degenerate constant-score model would score a perfect ranking.
    ties = (cand == held_scores[:, None]).sum(axis=1) - 1
    return better + 0.5 * ties


def recall_at_k(
    scores: np.ndarray, train: RatingsCOO, heldout: Heldout, k: int = 10
) -> float:
    """Fraction of held-out items ranked in the user's top-K unseen items."""
    if heldout.user_dense.size == 0:
        raise ValueError("empty heldout set")
    return float((_ranks(scores, train, heldout) < k).mean())


def mean_percentile_rank(
    scores: np.ndarray, train: RatingsCOO, heldout: Heldout
) -> float:
    """Hu et al.'s MPR ∈ [0, 1]; 0.5 = random, lower is better."""
    if heldout.user_dense.size == 0:
        raise ValueError("empty heldout set")
    num_candidates = scores.shape[1] - np.bincount(
        train.user_raw, minlength=scores.shape[0]
    )[heldout.user_dense]
    ranks = _ranks(scores, train, heldout)
    return float((ranks / np.maximum(num_candidates - 1, 1)).mean())
