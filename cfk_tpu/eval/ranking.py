"""Ranking evaluation for the implicit model: leave-one-out Recall@K / MPR.

The reference's only metric is observed-cell MSE (``scripts/calculate_mse.py``);
implicit feedback needs ranking metrics instead — each held-out item is
ranked among all items the user has NOT interacted with in training.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cfk_tpu.data.blocks import RatingsCOO


@dataclasses.dataclass(frozen=True)
class Heldout:
    user_dense: np.ndarray  # [n] dense user index
    movie_dense: np.ndarray  # [n] dense movie index of the held-out item


def leave_one_out_split(
    movie_dense: np.ndarray,
    user_dense: np.ndarray,
    rating: np.ndarray,
    *,
    seed: int = 0,
) -> tuple[RatingsCOO, Heldout]:
    """Hold out one random interaction per user with ≥ 2 interactions.

    Inputs are dense-index COO arrays; returns (train COO in dense indices,
    heldout).  Users with a single interaction keep it in train, and an
    interaction is only held out while its movie retains ≥ 2 interactions —
    so every entity stays covered in train and the dense index space of a
    Dataset built from ``train`` coincides with the full dataset's (holding
    out a movie's last interaction would silently shift all later movie
    indices and mis-align ranking evaluation).
    """
    rng = np.random.default_rng(seed)
    order = rng.permutation(user_dense.shape[0])
    held_mask = np.zeros(user_dense.shape[0], dtype=bool)
    user_counts = np.bincount(user_dense)
    movie_counts = np.bincount(movie_dense)
    seen: set[int] = set()
    for idx in order:
        u = int(user_dense[idx])
        mv = int(movie_dense[idx])
        if u not in seen and user_counts[u] >= 2 and movie_counts[mv] >= 2:
            held_mask[idx] = True
            seen.add(u)
            movie_counts[mv] -= 1
    train = RatingsCOO(
        movie_raw=movie_dense[~held_mask].astype(np.int64),
        user_raw=user_dense[~held_mask].astype(np.int64),
        rating=rating[~held_mask].astype(np.float32),
    )
    heldout = Heldout(
        user_dense=user_dense[held_mask].astype(np.int64),
        movie_dense=movie_dense[held_mask].astype(np.int64),
    )
    return train, heldout


def _validate_index_space(train: RatingsCOO, num_users: int, num_movies: int,
                          what: str) -> None:
    if train.user_raw.max(initial=-1) >= num_users or train.movie_raw.max(
        initial=-1
    ) >= num_movies:
        raise ValueError(
            f"train indices exceed {what} ({num_users} users, {num_movies} "
            "movies) — the model was trained on a dataset with a different "
            "dense index space than the split; build the split with "
            "leave_one_out_split so every entity stays covered in train"
        )


def _tie_averaged_ranks(cand: np.ndarray, held_scores: np.ndarray) -> np.ndarray:
    """0-based rank of ``held_scores[i]`` within row ``cand[i]`` (train cells
    already -inf).  Ties count half (excluding the held item's own cell) —
    otherwise a degenerate constant-score model would score a perfect
    ranking.  The one copy of the rank semantics shared by the dense and
    chunked evaluators."""
    better = (cand > held_scores[:, None]).sum(axis=1)
    ties = (cand == held_scores[:, None]).sum(axis=1) - 1
    return better + 0.5 * ties


def _num_candidates(train: RatingsCOO, heldout: Heldout, num_users: int,
                    num_movies: int) -> np.ndarray:
    """Per-held-out-user count of non-train items (the MPR denominator)."""
    return num_movies - np.bincount(
        train.user_raw, minlength=num_users
    )[heldout.user_dense]


def _ranks(
    scores: np.ndarray,  # [num_users, num_movies]
    train: RatingsCOO,  # dense-index COO of training interactions
    heldout: Heldout,
) -> np.ndarray:
    """0-based rank of each held-out item among that user's non-train items."""
    _validate_index_space(
        train, scores.shape[0], scores.shape[1], f"score matrix {scores.shape}"
    )
    s = scores.copy()
    s[train.user_raw, train.movie_raw] = -np.inf  # exclude seen items
    held_scores = s[heldout.user_dense, heldout.movie_dense]
    return _tie_averaged_ranks(s[heldout.user_dense], held_scores)


def ranks_from_model(
    model, train: RatingsCOO, heldout: Heldout, chunk: int = 8192
) -> np.ndarray:
    """0-based tie-averaged rank of each held-out item, streamed in chunks.

    Semantics match ``_ranks`` on the dense score matrix exactly, but scores
    are computed per held-out-user chunk ([chunk, num_movies] at a time), so
    the eval works at scales where U·Mᵀ cannot be materialized — the same
    generalization ``mse_rmse_from_model`` makes for the MSE eval.
    """
    u, m = model.host_factors()
    _validate_index_space(train, u.shape[0], m.shape[0], "factor shapes")
    # CSR of train interactions by user, for per-chunk exclusion.
    order = np.argsort(train.user_raw, kind="stable")
    tm = train.movie_raw[order].astype(np.int64)
    starts = np.searchsorted(train.user_raw[order], np.arange(u.shape[0] + 1))
    out = np.empty(heldout.user_dense.shape[0], dtype=np.float64)
    for lo in range(0, heldout.user_dense.shape[0], chunk):
        hu = heldout.user_dense[lo : lo + chunk]
        hm = heldout.movie_dense[lo : lo + chunk]
        cand = u[hu] @ m.T  # [c, num_movies]
        counts = starts[hu + 1] - starts[hu]
        rows = np.repeat(np.arange(hu.shape[0]), counts)
        flat = np.arange(counts.sum()) + np.repeat(
            starts[hu] - np.concatenate(([0], np.cumsum(counts[:-1]))), counts
        )
        cand[rows, tm[flat]] = -np.inf  # exclude seen items
        held_scores = cand[np.arange(hu.shape[0]), hm]
        out[lo : lo + hu.shape[0]] = _tie_averaged_ranks(cand, held_scores)
    return out


def ranking_metrics_from_model(
    model, train: RatingsCOO, heldout: Heldout, k: int = 10, chunk: int = 8192
) -> tuple[float, float]:
    """(Recall@K, MPR) straight from the factors — one rank pass, no dense P."""
    if heldout.user_dense.size == 0:
        raise ValueError("empty heldout set")
    ranks = ranks_from_model(model, train, heldout, chunk)
    nc = _num_candidates(train, heldout, model.num_users, model.num_movies)
    recall = float((ranks < k).mean())
    mpr = float((ranks / np.maximum(nc - 1, 1)).mean())
    return recall, mpr


def recall_at_k(
    scores: np.ndarray, train: RatingsCOO, heldout: Heldout, k: int = 10
) -> float:
    """Fraction of held-out items ranked in the user's top-K unseen items."""
    if heldout.user_dense.size == 0:
        raise ValueError("empty heldout set")
    return float((_ranks(scores, train, heldout) < k).mean())


def mean_percentile_rank(
    scores: np.ndarray, train: RatingsCOO, heldout: Heldout
) -> float:
    """Hu et al.'s MPR ∈ [0, 1]; 0.5 = random, lower is better."""
    if heldout.user_dense.size == 0:
        raise ValueError("empty heldout set")
    nc = _num_candidates(train, heldout, scores.shape[0], scores.shape[1])
    ranks = _ranks(scores, train, heldout)
    return float((ranks / np.maximum(nc - 1, 1)).mean())
