"""Prediction-matrix CSV dump, wire-compatible with the reference's output.

The reference dumps via EJML ``MatrixIO.saveDenseCSV``
(``processors/FeatureCollector.java:96-109``): a header line
``<numRows> <numCols> real`` followed by space-separated rows.  The offline
evaluator skips any line containing "real" (``scripts/calculate_mse.py:66-68``),
so this format keeps ``calculate_mse.py`` drop-in usable against our output.
"""

from __future__ import annotations

import os
import time

import numpy as np


def save_prediction_csv(predictions: np.ndarray, path: str | None = None) -> str:
    """Write the dense prediction matrix in EJML dense-CSV format.

    If ``path`` is None, writes ``predictions/prediction_matrix_<epoch-ms>``
    like the reference (``processors/FeatureCollector.java:96-100``).
    """
    if path is None:
        os.makedirs("predictions", exist_ok=True)
        path = os.path.join("predictions", f"prediction_matrix_{int(time.time() * 1000)}")
    rows, cols = predictions.shape
    with open(path, "w") as f:
        f.write(f"{rows} {cols} real\n")
        np.savetxt(f, predictions.astype(np.float64), fmt="%.9g", delimiter=" ")
    return path


def load_prediction_csv(path: str) -> np.ndarray:
    """Read an EJML dense-CSV prediction matrix (header line skipped)."""
    with open(path) as f:
        header = f.readline().split()
        rows, cols = int(header[0]), int(header[1])
        mat = np.loadtxt(f, dtype=np.float64)
    return mat.reshape(rows, cols)
