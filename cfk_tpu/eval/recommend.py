"""Top-K recommendation serving: the online-query analog of the batch dump.

The reference's only serving artifact is the full dense prediction matrix
written to CSV at the end of training (``processors/FeatureCollector.java:
90-109``) — O(users × movies) disk for any query.  Here the same factors
answer top-K queries directly: one [n, k]·[k, M] MXU matmul per user chunk +
``lax.top_k``, with already-rated items excluded via a trash-column scatter
(no O(U×M) materialization anywhere).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_chunk(u_rows, movie_factors, seen_idx, seen_mask, k):
    """(values, movie_indices) of the top-k unseen movies per user row.

    ``seen_idx`` [n, S] holds each row's already-rated movie columns, padded
    with ``num_movies`` (a trash column appended before the scatter, dropped
    after) so padding never masks a real movie.
    """
    n = u_rows.shape[0]
    scores = jnp.einsum(
        "nk,mk->nm",
        u_rows.astype(jnp.float32),
        movie_factors.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    scores = jnp.concatenate(
        [scores, jnp.zeros((n, 1), scores.dtype)], axis=1
    )
    neg = jnp.where(seen_mask, -jnp.inf, 0.0)
    scores = scores.at[jnp.arange(n)[:, None], seen_idx].add(neg)
    return jax.lax.top_k(scores[:, :-1], k)


def _seen_lists(user_rows: np.ndarray, dataset, num_movies: int):
    """Padded [n, S] seen-movie columns (+mask) for the requested user rows."""
    coo = dataset.coo_dense
    uniq, inv = np.unique(user_rows, return_inverse=True)
    n = uniq.shape[0]
    row_of_user = np.full(int(coo.user_raw.max(initial=-1)) + 2, -1, dtype=np.int64)
    row_of_user[uniq] = np.arange(n)
    sel = np.flatnonzero(row_of_user[coo.user_raw] >= 0)
    rows = row_of_user[coo.user_raw[sel]]
    movies = coo.movie_raw[sel]
    counts = np.bincount(rows, minlength=n)
    # Power-of-two width: the seen-list rectangle shape feeds a jitted
    # function, so a data-dependent exact width would recompile per chunk.
    width = max(8, 1 << (max(int(counts.max(initial=0)), 1) - 1).bit_length())
    seen_idx = np.full((n, width), num_movies, dtype=np.int32)  # trash column
    seen_mask = np.zeros((n, width), dtype=np.float32)
    order = np.argsort(rows, kind="stable")
    pos = np.arange(sel.size) - np.concatenate(([0], np.cumsum(counts)))[rows[order]]
    seen_idx[rows[order], pos] = movies[order].astype(np.int32)
    seen_mask[rows[order], pos] = 1.0
    return seen_idx[inv], seen_mask[inv]


def recommend_top_k(
    model,
    user_rows,
    k: int = 10,
    *,
    dataset=None,
    chunk: int = 8192,
):
    """Top-K movie rows (dense ascending-id indices) for each user row.

    ``dataset`` (anything with a dense-index ``.coo_dense`` — a training
    ``Dataset`` or a cheap ``RatingsIndex``) enables exclude-seen: movies the
    user already rated never appear in their recommendations.  Users are
    scored in ``chunk``-sized batches so serving memory stays
    O(chunk · num_movies).  Returns (scores [n, k], movie_rows [n, k]) as
    numpy arrays.
    """
    user_rows = np.asarray(user_rows, dtype=np.int64)
    if user_rows.ndim != 1:
        raise ValueError(f"user_rows must be 1-D, got shape {user_rows.shape}")
    if np.any((user_rows < 0) | (user_rows >= model.num_users)):
        raise ValueError(
            f"user rows out of range [0, {model.num_users}): "
            f"{user_rows[(user_rows < 0) | (user_rows >= model.num_users)][:5]}"
        )
    if not 1 <= k <= model.num_movies:
        raise ValueError(f"k must be in [1, {model.num_movies}], got {k}")
    user_factors, movie_factors = model.user_factors, model.movie_factors
    if not getattr(user_factors, "is_fully_addressable", True):
        # Multi-process sharded factors can't be indexed from one controller;
        # gather once (small [E, k] matrices) and serve from host copies.
        from cfk_tpu.parallel.mesh import to_host

        user_factors = to_host(user_factors)
        movie_factors = to_host(movie_factors)
    m = movie_factors[: model.num_movies]
    out_scores = np.empty((user_rows.shape[0], k), dtype=np.float32)
    out_movies = np.empty((user_rows.shape[0], k), dtype=np.int32)
    for lo in range(0, user_rows.shape[0], chunk):
        rows = user_rows[lo : lo + chunk]
        u = user_factors[rows]  # numpy or jax factors both index fine
        if dataset is not None:
            seen_idx, seen_mask = _seen_lists(rows, dataset, model.num_movies)
        else:
            seen_idx = np.full((rows.shape[0], 1), model.num_movies, np.int32)
            seen_mask = np.zeros((rows.shape[0], 1), np.float32)
        values, idx = _topk_chunk(
            u, m, jnp.asarray(seen_idx), jnp.asarray(seen_mask), k
        )
        out_scores[lo : lo + rows.shape[0]] = np.asarray(values)
        out_movies[lo : lo + rows.shape[0]] = np.asarray(idx)
    return out_scores, out_movies
