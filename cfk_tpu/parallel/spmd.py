"""SPMD sharded ALS: the distributed half-iteration as explicit collectives.

The reference's per-iteration feature-exchange Kafka topics
(``apps/ALSApp.java:115-151``) become one collective per half-iteration:

- ``all_gather`` exchange — every shard receives the full fixed-side factor
  matrix over ICI, then solves its local entities.  This is the all-to-all
  join (reference's ``all-to-all-join`` branch, README.md:172) done right:
  the OutBlock send-once-per-partition dedup
  (``processors/MRatings2BlocksProcessor.java:63-65``) is exactly what
  all_gather gives for free.

- ``ring`` exchange — fixed-side factor *blocks* rotate around the shard ring
  via ``ppermute``; each shard accumulates the partial Gram matrix of the
  block it currently holds.  This is the block-to-block join
  (README.md:152-157) as a systolic ring — the ring-attention-style pattern:
  per-device memory stays O(F/S·k) instead of O(F·k), at the cost of S
  pipeline steps whose compute hides the permute latency.

The EOF barrier protocol of the reference (``processors/URatings2BlocksProcessor.java:56-63``)
has no runtime analog here: bulk-synchronous SPMD steps *are* the barrier
(SURVEY.md §2.6); the ingest-side protocol lives in ``cfk_tpu.transport``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cfk_tpu.compat import shard_map as _compat_shard_map, to_varying
from cfk_tpu.config import ALSConfig
from cfk_tpu.data.blocks import (
    BucketedBlocks,
    Dataset,
    PaddedBlocks,
    RingBlocks,
    SegmentBlocks,
    TiledBlocks,
    build_ring_blocks,
)
from cfk_tpu.models.als import ALSModel
from cfk_tpu.ops.solve import (
    _match_varying,
    als_half_step,
    als_half_step_bucketed,
    als_half_step_segment,
    gather_gram,
    global_gram,
    init_factors,
    init_factors_stats,
    regularized_solve,
)
from cfk_tpu.parallel.mesh import AXIS, shard_rows, to_host


_to_varying = to_varying  # compat: pcast / pvary / identity by jax version


def half_step_allgather(
    fixed_local, nb, rt, mk, cnt, *, lam, solve_chunk=None, solver="cholesky",
    table_dtype=None,
):
    """Per-shard half-iteration with all_gather'd fixed factors.

    Runs inside shard_map: all args are local shards (entity axis 0).
    ``table_dtype="bfloat16"`` quantizes the exchange payload BEFORE the
    all_gather (half the ICI bytes), which is also the gather-table cast
    downstream — per-row quantization commutes with row sharding.
    """
    from cfk_tpu.ops import quant

    fixed_full = lax.all_gather(
        quant.gather_operand_view(fixed_local, table_dtype),
        AXIS, axis=0, tiled=True,
    )
    return als_half_step(
        fixed_full, nb, rt, mk, cnt, lam, solve_chunk=solve_chunk, solver=solver
    )


def _gram_chunked(blk, nb_t, rt_t, mk_t, solve_chunk, overlap=None):
    """gather_gram over entity chunks: bounds the [chunk, P_ring, k] gather.

    An indivisible entity count is padded with zero-mask rows (their Grams
    are exact zeros, sliced off), so budget-derived chunk sizes always
    work.  The chunk stream is double-buffered (``ops.pipeline.chunk_map``):
    chunk c+1's operand fetch is issued while chunk c's Gram runs."""
    if solve_chunk is None or solve_chunk >= nb_t.shape[0]:
        return gather_gram(blk, nb_t, rt_t, mk_t)
    from cfk_tpu.ops.pipeline import chunk_map
    from cfk_tpu.ops.solve import pad_rows_to_multiple

    e = nb_t.shape[0]
    (nb_t, rt_t, mk_t), pad = pad_rows_to_multiple(
        (nb_t, rt_t, mk_t), solve_chunk
    )
    n_chunks = (e + pad) // solve_chunk
    reshape = lambda x: x.reshape((n_chunks, solve_chunk) + x.shape[1:])
    a, b = chunk_map(
        lambda ni, ri, mi: gather_gram(blk, ni, ri, mi),
        (reshape(nb_t), reshape(rt_t), reshape(mk_t)),
        n_chunks, overlap=overlap,
    )
    k = blk.shape[-1]
    return a.reshape(e + pad, k, k)[:e], b.reshape(e + pad, k)[:e]


def _ring_rotate(blk, perm, compute, *, overlap):
    """One double-buffered ring step: the next block's ``ppermute`` is
    issued BEFORE the Gram consumes the current one (two factor buffers
    alive — the classic double buffer), so XLA's async collective-permute
    scheduling can run the ICI transfer under the compute.  With
    ``overlap=False`` an ``optimization_barrier`` pins the serial reference
    schedule (compute fully drains, then the transfer starts) — the A/B
    ``bench.py --overlap-ab`` measures.  Returns (compute result, next
    block); both orders run identical ops on identical values, so factors
    are bit-equal either way (``tests/test_overlap.py``)."""
    permute = lambda b: jax.tree.map(
        lambda x: lax.ppermute(x, AXIS, perm), b
    )  # blk may be a (data, scale) tuple — quantized tables rotate both
    if overlap:
        nxt = permute(blk)
        out = compute(blk)
    else:
        out = compute(blk)
        out, blk = lax.optimization_barrier((out, blk))
        nxt = permute(blk)
    return out, nxt


def _nonfinite_flag(x):
    """int32 0/1: any NaN/Inf anywhere in ``x`` (ring-carry health probe)."""
    return jnp.where(
        jnp.all(jnp.isfinite(x.astype(jnp.float32))),
        jnp.int32(0), jnp.int32(1),
    )


def _payload_nonfinite_flag(tbl):
    """Ring-payload probe over the LAST leaf: the f32/bf16 factor block
    itself, or the int8 pair's f32 per-row scales.  The int8 codes are
    finite by construction, so probing them would miss every corruption;
    ``quant.quantize_table`` propagates a corrupt row's NaN/Inf into its
    scale, making the scales the one int8 leaf that can trip."""
    return _nonfinite_flag(tbl[-1])


def half_step_ring(
    fixed_local, nb, rt, mk, cnt, *, lam, num_shards, solve_chunk=None,
    solver="cholesky", overlap=None, probe=None, fused_epilogue=None,
    health=False, reg_solve_algo=None, table_dtype=None,
):
    """Per-shard half-iteration accumulating Gram blocks around a ppermute ring.

    ``nb/rt/mk`` are RingBlocks locals: [E_local, S, P_ring] with neighbor
    indices local to the fixed shard that owns them.  At ring step r this
    shard holds the factor block of fixed shard (my_index − r) mod S; the
    final step's block is consumed without a trailing ppermute (S−1 transfers
    per half-iteration, not S).

    The loop is a double-buffered pipeline (``_ring_rotate``): block r+1's
    transfer is in flight while block r's Gram accumulates.  ``probe``
    (timing-only, used by the bench's exchange/compute split) runs just the
    transfers ("exchange") or just the Gram/solve with no transfers
    ("compute") — same op counts as the respective phase of the real
    half-iteration, numerically meaningless factors.

    ``health=True`` (the resilience sentinel's ring-carry probe,
    ``cfk_tpu.resilience``) folds an ``isfinite`` check of each
    ring-rotated factor block into the loop carry and returns
    ``(factors, bad)`` — ``bad`` is a per-shard int32 flag that localizes
    in-flight exchange corruption to this half-iteration instead of
    waiting for it to surface in the solved factors.  Incompatible with
    the timing ``probe`` modes (which compute meaningless factors).
    """
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.pipeline import resolve_overlap

    if health and probe is not None:
        raise ValueError("health probing and timing probes are exclusive")
    overlap = resolve_overlap(overlap)
    # Quantize the ROTATING payload once, before the ring: every ppermute
    # then moves the bf16 block (half the ICI bytes) and every Gram
    # consumes the same quantized rows — the padded layout's weight-free
    # Gram admits bf16 only (config validation refuses int8 here).
    fixed_local = quant.gather_operand_view(fixed_local, table_dtype)
    my = lax.axis_index(AXIS)
    e = nb.shape[0]
    k = fixed_local.shape[-1]
    perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]

    def gram_at(blk, r):
        t = (my - r) % num_shards
        return _gram_chunked(
            blk,
            jnp.take(nb, t, axis=1),
            jnp.take(rt, t, axis=1),
            jnp.take(mk, t, axis=1),
            solve_chunk,
            overlap,
        )

    if probe == "exchange":  # transfers only; factors are a timing sink
        blk = lax.fori_loop(
            0, num_shards - 1,
            lambda r, blk: lax.ppermute(blk, AXIS, perm),
            fixed_local,
        )
        return jnp.zeros((e, k), jnp.float32) + jnp.sum(blk).astype(
            jnp.float32
        )

    def body(r, carry):
        a, b, blk, bad = carry
        if health:
            bad = bad | _nonfinite_flag(blk)
        if probe == "compute":  # Gram/solve only: never rotate the block
            ap, bp = gram_at(blk, r)
            return (a + ap, b + bp, blk, bad)
        (ap, bp), blk = _ring_rotate(
            blk, perm, lambda cur: gram_at(cur, r), overlap=overlap
        )
        return (a + ap, b + bp, blk, bad)

    # Mark the zero accumulators device-varying so the fori_loop carry type
    # matches the (varying) per-shard partial Gram sums.
    a0 = _to_varying(jnp.zeros((e, k, k), jnp.float32), AXIS)
    b0 = _to_varying(jnp.zeros((e, k), jnp.float32), AXIS)
    bad0 = _to_varying(jnp.zeros((), jnp.int32), AXIS)
    a, b, blk, bad = lax.fori_loop(
        0, num_shards - 1, body, (a0, b0, fixed_local, bad0)
    )
    if health:
        bad = bad | _nonfinite_flag(blk)
    ap, bp = gram_at(blk, num_shards - 1)
    # The ring's (A, b) accumulates ACROSS ring steps, so there is no
    # per-chunk VMEM residency to solve inside; ``fused_epilogue`` gates
    # the one fused reg+solve pass over the final sums (the fused/split
    # A/B axis).
    x = regularized_solve(a + ap, b + bp, cnt, lam, solver,
                          fused=fused_epilogue, algo=reg_solve_algo)
    return (x, bad) if health else x


def _segment_to_tree(blocks: SegmentBlocks) -> dict[str, np.ndarray]:
    """Flat per-shard packed chunks; every leaf rows-shards over P(AXIS)."""
    return {
        "neighbor": blocks.neighbor_idx,
        "rating": blocks.rating,
        "mask": blocks.mask,
        "seg": blocks.seg_rel,
        "entity": blocks.chunk_entity,
        "ecount": blocks.chunk_count,
        "gsizes": blocks.group_sizes,
        "cin": blocks.carry_in,
        "lseg": blocks.last_seg,
    }


# Both exchange layouts expose the same tree keys; "neighbor" holds dense
# global indices for all_gather blocks, shard-local indices for ring blocks.
def _padded_to_tree(blocks: PaddedBlocks) -> dict[str, np.ndarray]:
    return {
        "neighbor": blocks.neighbor_idx,
        "rating": blocks.rating,
        "mask": blocks.mask,
        "count": blocks.count,
    }


def _ring_to_tree(blocks: RingBlocks) -> dict[str, np.ndarray]:
    return {
        "neighbor": blocks.neighbor_local,
        "rating": blocks.rating,
        "mask": blocks.mask,
        "count": blocks.count,
    }


def _bucketed_to_tree(blocks: BucketedBlocks):
    """Tuple-of-dicts pytree (shard-major rows, P(AXIS) shardable) + static
    per-bucket chunk hints."""
    return blocks.to_tree()


def tree_specs(tree):
    return jax.tree.map(
        lambda v: P(AXIS, *([None] * (v.ndim - 1))), tree
    )


_tree_specs = tree_specs  # back-compat alias


def wrap_step(mesh, config: ALSConfig, half_m, half_u, mspecs, uspecs,
              *, carry_prev=False, ring_flags=False):
    """The one shard_map scaffold every training step shares.

    ``half_m``/``half_u`` map (fixed_local, local_block_tree) → new local
    factors for one side; the wrapper sequences the two half-iterations,
    casts factors to the storage/exchange dtype, and binds the row shardings.
    With ``carry_prev`` (warm-started optimizers like iALS++) the halves get
    the side's previous local factors too: (fixed_local, prev_local, blk).

    With ``ring_flags`` (the resilience sentinel's ring-carry probe) every
    half returns ``(factors, bad)`` and the step emits a third, replicated
    int32 output: the psum of both halves' per-shard exchange-corruption
    flags — 0 means every ring-rotated block stayed finite on every shard.
    """
    dtype = jnp.dtype(config.dtype)

    def solve_half(half, fixed, prev, blk):
        out = half(fixed, prev, blk) if carry_prev else half(fixed, blk)
        x, bad = out if ring_flags else (out, None)
        return x.astype(dtype), bad

    def iteration(u, m_prev, mblk, ublk):
        m, bad_m = solve_half(half_m, u, m_prev, mblk)
        u_new, bad_u = solve_half(half_u, m, u, ublk)
        if not ring_flags:
            return u_new, m
        return u_new, m, lax.psum(bad_m + bad_u, AXIS)

    out_specs = (P(AXIS, None), P(AXIS, None))
    if ring_flags:
        out_specs = out_specs + (P(),)
    return _compat_shard_map(
        iteration,
        mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None), mspecs, uspecs),
        out_specs=out_specs,
        check=use_check_vma(config),
    )


def gathered_half(solve, *, with_gram=False, with_prev=False,
                  table_dtype=None):
    """The all_gather exchange pattern every gathered layout shares.

    ``solve(fixed_full, blk, gram) -> factors`` gets the full fixed-side
    factor matrix (one all_gather over ICI per half-iteration) and, with
    ``with_gram`` (iALS), the mesh-wide YᵀY (local Gram psum'd — a [k,k]
    collective).  ``with_prev`` threads the side's previous local factors
    through as ``solve(fixed_full, prev_local, blk, gram)`` (iALS++ warm
    start; the sweep is per-entity so prev stays shard-local, no extra
    collective).  Used by the explicit and implicit SPMD steps so the
    exchange is written exactly once.

    ``table_dtype="bfloat16"`` casts the exchange payload BEFORE the
    all_gather (half the ICI bytes; per-row quantization commutes with
    row sharding, so the gathered table equals the single-device cast and
    the downstream half-step's own cast is idempotent).  int8 payloads
    are NOT pre-quantized here — the (codes, scales) pair would double
    the collective count for a path whose bytes win is in the HBM
    gathers; the downstream half-step quantizes the gathered table
    instead.  The iALS gram is computed over the DEQUANTIZED local view
    either way, so YᵀY matches what the kernels gather.
    """
    from cfk_tpu.ops import quant

    def _prep(fixed_local):
        gram = None
        if with_gram:
            gram = lax.psum(
                global_gram(
                    quant.gather_operand_view(fixed_local, table_dtype)
                ),
                AXIS,
            )
        payload = fixed_local
        if quant.resolve_table_dtype(table_dtype) == "bfloat16":
            payload = payload.astype(jnp.bfloat16)
        return lax.all_gather(payload, AXIS, axis=0, tiled=True), gram

    def half(fixed_local, blk):
        fixed_full, gram = _prep(fixed_local)
        return solve(fixed_full, blk, gram)

    def half_prev(fixed_local, prev_local, blk):
        fixed_full, gram = _prep(fixed_local)
        return solve(fixed_full, prev_local, blk, gram)

    return half_prev if with_prev else half


def _tiled_to_tree(blocks: TiledBlocks, weighted: bool = False
                   ) -> dict[str, np.ndarray]:
    """Flat per-shard tiled arrays; every leaf rows-shards over P(AXIS)."""
    if blocks.mode == "dstream":
        d = {
            "neighbor_idx": blocks.neighbor_idx,
            "rating": blocks.rating,
            "tile_meta": blocks.tile_meta,
            "chunk_entity": blocks.chunk_entity,
            "chunk_count": blocks.chunk_count,
            "carry_in": blocks.carry_in,
            "last_seg": blocks.last_seg,
            "count": blocks.count,
        }
        if weighted:
            if not blocks.weight.size or blocks.rating_dense is None:
                raise ValueError(
                    "these dense-stream blocks predate the weighted "
                    "channels — rebuild the dataset (delete its cache)"
                )
            d["weight"] = blocks.weight
            d["rating_dense"] = blocks.rating_dense
        return d
    return {
        "neighbor_idx": blocks.neighbor_idx,
        "rating": blocks.rating,
        "weight": blocks.weight,
        "tile_seg": blocks.tile_seg,
        "chunk_base": blocks.chunk_base,
        "chunk_entity": blocks.chunk_entity,
        "chunk_count": blocks.chunk_count,
        "carry_in": blocks.carry_in,
        "last_seg": blocks.last_seg,
        "slice_starts": blocks.slice_starts,
        "count": blocks.count,
    }


def _make_tiled_slice_grams(blk, *, cap, nt, e_c, t, k, backend, gather,
                            int8):
    """The per-slice chunk loop both tiled ring schedules share: scan the
    slice's chunks against whichever factor block this shard currently
    holds, scatter-adding chunk-dense per-entity Grams into the persistent
    accumulator.  Factored out of ``half_step_tiled_ring`` so the flat and
    hierarchical rings run the IDENTICAL per-slice ops (the hierarchy only
    reorders which block arrives when)."""
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.tiled import _entity_gram_chunk

    nb, rt, wt = blk["neighbor_idx"], blk["rating"], blk["weight"]
    ts, ent = blk["tile_seg"], blk["chunk_entity"]
    starts = blk["slice_starts"]  # [S+1]

    def slice_grams(acc, tbl, t_idx):
        factors = tbl[0]
        scale_blk = tbl[1] if int8 else None
        # One zero-row append per ring step, not per chunk (the chunk-scan
        # body would otherwise re-copy the whole block every chunk); the
        # in-kernel gather skips even that — the kernel DMAs from the raw
        # rotated block and the weight channel masks the padding rows.
        if gather == "fused":
            fz = factors
        else:
            fz = jnp.concatenate([
                factors,
                _match_varying(
                    jnp.zeros((1, k), factors.dtype), factors
                ),
            ])

        def chunk_body(i, acc):
            acc_a, acc_b = acc
            nb_c = lax.dynamic_slice(nb, (i * cap,), (cap,))
            rt_c = lax.dynamic_slice(rt, (i * cap,), (cap,))
            wt_c = lax.dynamic_slice(wt, (i * cap,), (cap,))
            ts_c = lax.dynamic_slice(ts, (i * nt,), (nt,))
            ent_c = lax.dynamic_slice(ent, (i * e_c,), (e_c,))
            # int8: fold this block's per-row dequant scale into the 0/1
            # weight channel (nb is local to the rotated block; the
            # block-local virtual zero row gets the appended 0 scale).
            wt_c = quant.fold_scale(wt_c, scale_blk, nb_c)
            a, b = _entity_gram_chunk(
                fz, nb_c, wt_c, rt_c, ts_c, t, e_c + 1, backend,
                # the ring is explicit-ALS only; int8 must premultiply
                # (the fold above IS the dequantize)
                unit_weights=not int8,
                zero_appended=gather != "fused", gather=gather,
            )
            return (acc_a.at[ent_c].add(a[:e_c]), acc_b.at[ent_c].add(b[:e_c]))

        return lax.fori_loop(starts[t_idx], starts[t_idx + 1], chunk_body, acc)

    return slice_grams


def resolve_ici_group(config: ALSConfig) -> int:
    """Inner-ring size of the hierarchical exchange: the explicit
    ``config.ici_group`` when set, else devices-per-process when that
    divides the shard count (the physical ICI domain on a multi-host
    mesh), else one flat ring (bit-identical to ``exchange='ring'``)."""
    if config.ici_group is not None:
        return config.ici_group
    local = jax.local_device_count()
    if 0 < local <= config.num_shards and config.num_shards % local == 0:
        return local
    return config.num_shards


def hier_phase_count(num_shards: int, inner: int) -> int:
    """Outer (DCN) phase count of the hierarchical exchange: the number
    of cross-group hops ``half_step_tiled_ring_hier`` rotates, and
    therefore the number of collectives the distributed window exchange
    runs per half.  ``inner == num_shards`` (the flat path) degenerates
    to one phase."""
    if inner < 1 or num_shards % inner != 0:
        raise ValueError(
            f"inner ring size {inner} must divide num_shards={num_shards}"
        )
    return num_shards // inner


def hier_phase_of_visit(visit_index: int, inner: int) -> int:
    """Which outer (DCN) phase a position in ``hier_visit_order``
    belongs to: the visit order walks ``inner`` ICI steps per outer hop,
    so phase = ``visit_index // inner``.  This is the cross-process
    delivery contract — a window's fixed-table residual must be on its
    consuming host by the start of the phase its slice is visited in."""
    if inner < 1:
        raise ValueError(f"inner ring size {inner} must be >= 1")
    return visit_index // inner


def half_step_tiled_ring_hier(
    fixed_local, blk, chunks, local_entities, *, lam, num_shards, inner,
    solver="cholesky", gram_backend=None, overlap=None, probe=None,
    fused_epilogue=None, health=False, in_kernel_gather=None,
    reg_solve_algo=None, table_dtype=None,
):
    """Hierarchical ICI-ring-within-DCN-ring tiled half-iteration
    (ISSUE 11; the ALX-style exchange for meshes whose fabric is tiered).

    ``num_shards = outer · inner``: shards group into ``inner``-sized
    rings on the fast fabric.  Phase ``p`` rotates each group's blocks
    ``inner − 1`` times over the INNER permutation (pure ICI — shard
    (g, i) visits every block of group g − p), then ONE outer hop moves
    every held block to the same inner position of the next group (the
    only transfers that cross DCN).  O·(I−1) + (O−1) = S−1 transfers, all
    S blocks visited per shard — the flat ring's totals, with the slow
    fabric paid O−1 times instead of on every boundary edge every step.

    Numerics: the per-slice chunk math is IDENTICAL to the flat ring
    (``_make_tiled_slice_grams``); only the VISIT ORDER of slices differs,
    so the per-entity Gram sums associate differently (same additions,
    different order — within float tolerance of the flat ring, and
    deterministic for a fixed (num_shards, inner)).  ``inner ==
    num_shards`` degenerates to one inner ring whose schedule — and
    factors — are BIT-IDENTICAL to ``half_step_tiled_ring``
    (tests/test_offload.py pins both contracts).  Each transfer is
    double-buffered via ``_ring_rotate`` exactly like the flat ring;
    ``probe``/``health`` as in ``half_step_tiled_ring``.
    """
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.pipeline import resolve_overlap
    from cfk_tpu.ops.tiled import (
        default_tiled_gram_backend,
        resolve_gather_mode,
    )

    if health and probe is not None:
        raise ValueError("health probing and timing probes are exclusive")
    s = num_shards
    if inner < 1 or s % inner != 0:
        raise ValueError(
            f"inner ring size {inner} must divide num_shards={s}"
        )
    outer = s // inner
    overlap = resolve_overlap(overlap)
    backend = gram_backend or default_tiled_gram_backend()
    _, _, nc, cap, t, h, e_c = chunks
    nt = cap // t
    k = fixed_local.shape[-1]
    gather = resolve_gather_mode(
        in_kernel_gather, backend, "full", cap, nt, t, e_c + 1, k,
    )
    data, scale = quant.quantize_table(fixed_local, table_dtype)
    tbl0 = (data,) if scale is None else (data, scale)
    int8 = scale is not None
    my = lax.axis_index(AXIS)
    g, i_pos = my // inner, my % inner
    # Inner rotation: within-group shift by one; outer hop: same inner
    # position of the next group.  Both are full permutations of [0, S).
    inner_perm = [
        (q, (q // inner) * inner + (q % inner + 1) % inner)
        for q in range(s)
    ]
    outer_perm = [
        (q, ((q // inner + 1) % outer) * inner + q % inner)
        for q in range(s)
    ]
    slice_grams = _make_tiled_slice_grams(
        blk, cap=cap, nt=nt, e_c=e_c, t=t, k=k, backend=backend,
        gather=gather, int8=int8,
    )

    # Schedule: (phase p, inner step j) — this shard holds the block of
    # slice (g − p, i + p − j); see the derivation in the docstring.
    # Rolled as fori loops (the flat ring's discipline): trace size is
    # O(1) in both `outer` and `inner`, not O(S) — an unrolled schedule
    # would trace S copies of the chunk loop at 64–256-shard meshes.
    def held(p, j):
        return ((g - p) % outer) * inner + (i_pos + p - j) % inner

    if probe == "exchange":  # transfers only; factors are a timing sink
        def x_inner(t):
            return lax.fori_loop(
                0, inner - 1,
                lambda j, tt: jax.tree.map(
                    lambda x: lax.ppermute(x, AXIS, inner_perm), tt
                ),
                t,
            )

        tbl = lax.fori_loop(
            0, outer - 1,
            lambda p, t: jax.tree.map(
                lambda x: lax.ppermute(x, AXIS, outer_perm), x_inner(t)
            ),
            tbl0,
        )
        tbl = x_inner(tbl)
        return jnp.zeros((local_entities, k), jnp.float32) + jnp.sum(
            tbl[0].astype(jnp.float32)
        )

    acc0 = (
        _to_varying(jnp.zeros((local_entities + 1, k, k), jnp.float32),
                    AXIS),
        _to_varying(jnp.zeros((local_entities + 1, k), jnp.float32), AXIS),
    )
    bad0 = _to_varying(jnp.zeros((), jnp.int32), AXIS)

    if probe == "compute":  # chunk loops only: never rotate the block
        def body(r, acc):
            return slice_grams(acc, tbl0, held(r // inner, r % inner))

        acc_a, acc_b = lax.fori_loop(0, inner * outer, body, acc0)
        x = regularized_solve(
            acc_a[:local_entities], acc_b[:local_entities],
            blk["count"], lam, solver, fused=fused_epilogue,
            algo=reg_solve_algo,
        )
        return x

    def inner_rotations(p, carry):
        """Phase ``p``'s first inner − 1 visits, each ending in an
        inner-ring rotation (j = 0 .. inner−2)."""
        def step(j, c):
            a, b, tbl, bad = c
            if health:
                bad = bad | _payload_nonfinite_flag(tbl)
            (a, b), tbl = _ring_rotate(
                tbl, inner_perm,
                lambda cur: slice_grams((a, b), cur, held(p, j)),
                overlap=overlap,
            )
            return a, b, tbl, bad

        return lax.fori_loop(0, inner - 1, step, carry)

    def phase_body(p, c):
        # inner − 1 inner rotations, then the phase's LAST visit ends in
        # the one outer (DCN) hop — no lax.cond around the collectives:
        # the hop is peeled out of the rolled inner loop.
        a, b, tbl, bad = inner_rotations(p, c)
        if health:
            bad = bad | _payload_nonfinite_flag(tbl)
        (a, b), tbl = _ring_rotate(
            tbl, outer_perm,
            lambda cur: slice_grams((a, b), cur, held(p, inner - 1)),
            overlap=overlap,
        )
        return a, b, tbl, bad

    carry = (acc0[0], acc0[1], tbl0, bad0)
    carry = lax.fori_loop(0, outer - 1, phase_body, carry)
    # Final phase: inner − 1 inner rotations, then the last visit
    # consumes the block without a trailing transfer (S − 1 total).
    a, b, tbl, bad = inner_rotations(outer - 1, carry)
    if health:
        bad = bad | _payload_nonfinite_flag(tbl)
    a, b = slice_grams((a, b), tbl, held(outer - 1, inner - 1))
    x = regularized_solve(
        a[:local_entities], b[:local_entities],
        blk["count"], lam, solver, fused=fused_epilogue,
        algo=reg_solve_algo,
    )
    return (x, bad) if health else x


def half_step_tiled_ring(
    fixed_local, blk, chunks, local_entities, *, lam, num_shards,
    solver="cholesky", gram_backend=None, overlap=None, probe=None,
    fused_epilogue=None, health=False, in_kernel_gather=None,
    reg_solve_algo=None, table_dtype=None,
):
    """Tiled-layout half-iteration over the ppermute ring (block-to-block
    join) — the reference's headline join strategy at the at-scale layout.

    The ring-built tiled blocks sort each shard's entries by (owner shard
    of the neighbor, entity) with slices exactly the fixed-side factor
    shards, so at ring step r the device processes slice (my − r) mod S —
    whose neighbor indices are local to the factor block it currently
    holds — and scatter-adds chunk-dense per-entity Grams into a
    persistent [E_local+1, ...] accumulator; one batched solve at the end.
    S − 1 ppermutes per half-iteration; the full fixed-side matrix is
    never materialized per device (O(F/S·k) factor memory, the
    block-to-block property), traded against the O(E_local·k²)
    accumulator the join needs on TPU — PARITY.md discusses when that
    trade wins.

    Each ring step is double-buffered (``_ring_rotate``): the next block's
    ppermute is issued before the current block's chunk loop starts, so
    the ICI transfer hides behind the slice's Gram accumulation.
    ``probe``/``overlap``/``health`` as in ``half_step_ring``.

    ``in_kernel_gather`` (default on where legal) fuses each chunk's
    neighbor gather into the Gram kernel (``ops.tiled`` ``gather="fused"``
    — the rotated factor block is the kernel's DMA source), which also
    retires the per-ring-step zero-row append of the whole block.
    """
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.pipeline import resolve_overlap
    from cfk_tpu.ops.tiled import (
        _entity_gram_chunk,
        default_tiled_gram_backend,
        resolve_gather_mode,
    )

    if health and probe is not None:
        raise ValueError("health probing and timing probes are exclusive")
    overlap = resolve_overlap(overlap)
    backend = gram_backend or default_tiled_gram_backend()
    _, _, nc, cap, t, h, e_c = chunks
    s = num_shards
    nt = cap // t
    k = fixed_local.shape[-1]
    gather = resolve_gather_mode(
        in_kernel_gather, backend, "full", cap, nt, t, e_c + 1, k,
    )
    # Quantize the ROTATING payload once, before the ring (ops.quant):
    # every ppermute then moves the bf16 block — or the (int8 codes,
    # f32 per-row scales) pair, a quarter of the bytes — and every Gram
    # consumes the quantized rows.  The int8 scale travels WITH its block
    # (indices are local to whichever block this shard currently holds),
    # folded into the weight channel per chunk — the canonical order.
    data, scale = quant.quantize_table(fixed_local, table_dtype)
    tbl0 = (data,) if scale is None else (data, scale)
    int8 = scale is not None
    my = lax.axis_index(AXIS)
    perm = [(i, (i + 1) % s) for i in range(s)]
    slice_grams = _make_tiled_slice_grams(
        blk, cap=cap, nt=nt, e_c=e_c, t=t, k=k, backend=backend,
        gather=gather, int8=int8,
    )

    if probe == "exchange":  # transfers only; factors are a timing sink
        tbl = lax.fori_loop(
            0, s - 1,
            lambda r, f: jax.tree.map(
                lambda x: lax.ppermute(x, AXIS, perm), f
            ),
            tbl0,
        )
        return jnp.zeros((local_entities, k), jnp.float32) + jnp.sum(
            tbl[0].astype(jnp.float32)
        )

    def body(r, carry):
        acc_a, acc_b, tbl, bad = carry
        t_idx = (my - r) % s
        if health:
            bad = bad | _payload_nonfinite_flag(tbl)
        if probe == "compute":  # chunk loops only: never rotate the block
            acc_a, acc_b = slice_grams((acc_a, acc_b), tbl, t_idx)
            return acc_a, acc_b, tbl, bad
        (acc_a, acc_b), tbl = _ring_rotate(
            tbl, perm,
            lambda cur: slice_grams((acc_a, acc_b), cur, t_idx),
            overlap=overlap,
        )
        return acc_a, acc_b, tbl, bad

    a0 = _to_varying(
        jnp.zeros((local_entities + 1, k, k), jnp.float32), AXIS
    )
    b0 = _to_varying(jnp.zeros((local_entities + 1, k), jnp.float32), AXIS)
    bad0 = _to_varying(jnp.zeros((), jnp.int32), AXIS)
    acc_a, acc_b, tbl, bad = lax.fori_loop(
        0, s - 1, body, (a0, b0, tbl0, bad0)
    )
    if health:
        bad = bad | _payload_nonfinite_flag(tbl)
    acc_a, acc_b = slice_grams(
        (acc_a, acc_b), tbl, (my - (s - 1)) % s
    )
    # Like accum mode, the ring's accumulator lives across steps in HBM;
    # the fused knob gates the final fused reg+solve vs the split
    # ridge-add + dispatch (bench.py --fused-ab measures the pair).
    x = regularized_solve(
        acc_a[:local_entities], acc_b[:local_entities],
        blk["count"], lam, solver, fused=fused_epilogue,
        algo=reg_solve_algo,
    )
    return (x, bad) if health else x


def gathered_layout_trees(dataset: Dataset, config: ALSConfig,
                          weighted: bool = False):
    """Block trees + step kwargs for the all_gather-only layouts.

    Returns (mtree, utree, step_kw) for bucketed/segment/tiled datasets —
    the setup shared by the explicit and implicit sharded trainers — or
    None when the dataset uses padded rectangles (caller picks
    per-exchange).  ``weighted=True`` (the iALS trainer) ships the
    dense-stream weighted channels too; explicit ALS skips their ~1 GB
    dead upload at full Netflix.
    """
    bucketed = isinstance(dataset.movie_blocks, BucketedBlocks)
    segment = isinstance(dataset.movie_blocks, SegmentBlocks)
    tiled = isinstance(dataset.movie_blocks, TiledBlocks)
    if not (bucketed or segment or tiled):
        return None
    ring = config.exchange in ("ring", "hier_ring")
    if ring and not tiled:
        name = "bucketed" if bucketed else "segment"
        raise ValueError(
            f"{name} layout supports exchange='all_gather' only — the ring "
            "join needs the owner-shard-sorted entry stream the padded and "
            "tiled layouts have, and tiled strictly dominates "
            f"{name} at ring-relevant scales (PARITY.md 'Known intentional "
            "divergences' #5); build the tiled dataset with "
            "Dataset.from_coo(..., ring=True) or ring='auto'"
        )
    if tiled and config.exchange != "auto":
        # "auto" takes each half's ring flag as built (the builder chose
        # per side); the explicit exchanges require matching blocks.
        for name, blocks in (("movie", dataset.movie_blocks),
                             ("user", dataset.user_blocks)):
            if ring != blocks.ring:
                raise ValueError(
                    f"config.exchange={config.exchange!r} but the tiled "
                    f"{name}_blocks were built with ring={blocks.ring}; "
                    f"rebuild with Dataset.from_coo(..., layout='tiled', "
                    f"ring={ring})"
                )
    if bucketed:
        mtree, m_chunks = _bucketed_to_tree(dataset.movie_blocks)
        utree, u_chunks = _bucketed_to_tree(dataset.user_blocks)
    elif tiled:
        mtree = _tiled_to_tree(dataset.movie_blocks, weighted)
        utree = _tiled_to_tree(dataset.user_blocks, weighted)
        m_chunks = ("tiled", dataset.movie_blocks.mode) + dataset.movie_blocks.statics
        u_chunks = ("tiled", dataset.user_blocks.mode) + dataset.user_blocks.statics
    else:
        mtree = _segment_to_tree(dataset.movie_blocks)
        utree = _segment_to_tree(dataset.user_blocks)
        m_chunks = dataset.movie_blocks.statics
        u_chunks = dataset.user_blocks.statics
    step_kw = dict(
        m_chunks=m_chunks,
        u_chunks=u_chunks,
        m_local=dataset.movie_blocks.local_entities,
        u_local=dataset.user_blocks.local_entities,
        segment=segment,
        tiled=tiled,
    )
    if tiled:
        step_kw.update(
            m_ring=dataset.movie_blocks.ring,
            u_ring=dataset.user_blocks.ring,
        )
    return mtree, utree, step_kw


def use_check_vma(config: ALSConfig) -> bool:
    """shard_map's varying-mesh-axes checker guards collective placement
    (e.g. the ring path's pvary), so keep it on whenever possible.  The one
    case it must be off: interpret-mode pallas kernels (CPU tests), whose
    interpreted jaxprs mix invariant constants with varying operands.  On
    real TPU the compiled kernel carries an explicit vma tag and passes."""
    return config.solver != "pallas" or jax.default_backend() == "tpu"


def _zero_flag(half, prev=False):
    """Append an always-clean exchange flag to a non-ring half so every
    half has the ``(factors, bad)`` shape ``wrap_step(ring_flags=True)``
    expects (all_gather halves have no in-flight carry to corrupt; any
    non-finite output is caught by the step-level factor probe)."""
    if prev:
        return lambda fixed, prev_local, blk: (
            half(fixed, prev_local, blk),
            _to_varying(jnp.zeros((), jnp.int32), AXIS),
        )
    return lambda fixed, blk: (
        half(fixed, blk), _to_varying(jnp.zeros((), jnp.int32), AXIS)
    )


def make_training_step(
    mesh: Mesh,
    config: ALSConfig,
    mspecs,
    uspecs=None,
    *,
    m_chunks=None,
    u_chunks=None,
    m_local=None,
    u_local=None,
    segment=False,
    tiled=False,
    m_ring=False,
    u_ring=False,
    ring_probe=None,
    health_probe=False,
):
    """Build the jittable one-full-iteration SPMD step (solve M, then U).

    Returned ``step(u, m, mblocks, ublocks) -> (u, m)`` operates on
    row-sharded global arrays; collectives are explicit inside shard_map.
    The bucketed layout (``m_chunks`` given) all_gathers the fixed side and
    solves each width bucket of the local shard; the segment layout
    (``segment=True``; ``m_chunks`` is then the static scan-window hint)
    all_gathers the fixed side and segment-sums the local flat rating run.

    ``config.overlap`` selects the double-buffered (comm/compute overlapped)
    ring and chunk schedules — the default — or the serial reference
    schedule; ``ring_probe`` ("exchange"/"compute", timing-only) builds the
    split-measurement step the bench's overlap A/B uses.

    ``health_probe=True`` (the resilience sentinel) makes the step return
    ``(u, m, bad)``: ring halves fold per-rotation ``isfinite`` checks of
    the in-flight factor block into their carry, non-ring halves
    contribute an always-clean flag, and ``bad`` is the mesh-wide psum —
    the resilient loop fetches it on the health cadence.
    """
    dtype = jnp.dtype(config.dtype)
    if health_probe and ring_probe is not None:
        raise ValueError("health probing and timing probes are exclusive")
    if uspecs is None:
        uspecs = mspecs

    def flagged(half, prev=False):
        return _zero_flag(half, prev) if health_probe else half

    if config.algorithm == "als++":
        from cfk_tpu.ops.subspace import (
            als_pp_half_step,
            als_pp_half_step_bucketed,
        )

        alg = dict(block_size=config.block_size, sweeps=config.sweeps,
                   solver=config.solver,
                   in_kernel_gather=config.in_kernel_gather,
                   fused_epilogue=config.fused_epilogue,
                   reg_solve_algo=config.reg_solve_algo,
                   table_dtype=config.table_dtype)

        if m_chunks is not None:  # bucketed layout

            def pp_bkt(chunks, local):
                def solve(fixed_full, prev_local, blk, _gram):
                    return als_pp_half_step_bucketed(
                        fixed_full, prev_local, blk, chunks, local,
                        config.lam, **alg,
                    )

                return solve

            return wrap_step(
                mesh, config,
                flagged(gathered_half(pp_bkt(m_chunks, m_local),
                                      with_prev=True,
                                      table_dtype=config.table_dtype),
                        prev=True),
                flagged(gathered_half(pp_bkt(u_chunks, u_local),
                                      with_prev=True,
                                      table_dtype=config.table_dtype),
                        prev=True),
                mspecs, uspecs, carry_prev=True, ring_flags=health_probe,
            )

        def pp_padded(fixed_full, prev_local, blk, _gram):
            return als_pp_half_step(
                fixed_full, prev_local, blk["neighbor"], blk["rating"],
                blk["mask"], blk["count"], config.lam, **alg,
            )

        half = flagged(gathered_half(pp_padded, with_prev=True,
                                     table_dtype=config.table_dtype),
                       prev=True)
        return wrap_step(mesh, config, half, half, mspecs, uspecs,
                         carry_prev=True, ring_flags=health_probe)

    if tiled:  # tile-padded layout

        from cfk_tpu.ops.tiled import tiled_half_step

        def ring_half(chunks, local):
            ring_kw = dict(
                lam=config.lam, num_shards=config.num_shards,
                solver=config.solver, overlap=config.overlap,
                probe=ring_probe,
                fused_epilogue=config.fused_epilogue,
                health=health_probe,
                in_kernel_gather=config.in_kernel_gather,
                reg_solve_algo=config.reg_solve_algo,
                table_dtype=config.table_dtype,
            )

            def half(fixed_local, blk):
                if config.exchange == "hier_ring":
                    return half_step_tiled_ring_hier(
                        fixed_local, blk, chunks, local,
                        inner=resolve_ici_group(config), **ring_kw,
                    )
                return half_step_tiled_ring(
                    fixed_local, blk, chunks, local, **ring_kw,
                )

            return half

        def ag_half(chunks, local):
            def solve(fixed_full, blk, _gram):
                return tiled_half_step(
                    fixed_full, blk, chunks, local, config.lam,
                    solver=config.solver, overlap=config.overlap,
                    fused_epilogue=config.fused_epilogue,
                    in_kernel_gather=config.in_kernel_gather,
                    reg_solve_algo=config.reg_solve_algo,
                    table_dtype=config.table_dtype,
                )

            return flagged(gathered_half(
                solve, table_dtype=config.table_dtype))

        # Each half picks its exchange from how its blocks were built —
        # exchange="auto" mixes them (ring movie-half + all_gather
        # user-half at Netflix shape, the per-side memory optimum);
        # "ring"/"all_gather" build both sides the same way.
        return wrap_step(
            mesh, config,
            (ring_half if m_ring else ag_half)(m_chunks, m_local),
            (ring_half if u_ring else ag_half)(u_chunks, u_local),
            mspecs, uspecs, ring_flags=health_probe,
        )

    if segment:  # flat segment layout, all_gather exchange

        def seg_solve(statics, local):
            def solve(fixed_full, blk, _gram):
                return als_half_step_segment(
                    fixed_full, blk["neighbor"], blk["rating"], blk["mask"],
                    blk["seg"], blk["entity"], blk["ecount"], blk["gsizes"],
                    blk["cin"], blk["lseg"], local,
                    config.lam, statics=statics, solver=config.solver,
                    reg_solve_algo=config.reg_solve_algo,
                )

            return solve

        return wrap_step(
            mesh, config,
            flagged(gathered_half(seg_solve(m_chunks, m_local),
                                  table_dtype=config.table_dtype)),
            flagged(gathered_half(seg_solve(u_chunks, u_local),
                                  table_dtype=config.table_dtype)),
            mspecs, uspecs, ring_flags=health_probe,
        )

    if m_chunks is not None:  # bucketed layout, all_gather exchange

        def bkt_solve(chunks, local):
            def solve(fixed_full, blk, _gram):
                return als_half_step_bucketed(
                    fixed_full, blk, chunks, local, config.lam,
                    solver=config.solver, overlap=config.overlap,
                    reg_solve_algo=config.reg_solve_algo,
                    fused_epilogue=config.fused_epilogue,
                    in_kernel_gather=config.in_kernel_gather,
                    table_dtype=config.table_dtype,
                )

            return solve

        return wrap_step(
            mesh, config,
            flagged(gathered_half(bkt_solve(m_chunks, m_local),
                                  table_dtype=config.table_dtype)),
            flagged(gathered_half(bkt_solve(u_chunks, u_local),
                                  table_dtype=config.table_dtype)),
            mspecs, uspecs, ring_flags=health_probe,
        )

    if config.exchange == "all_gather":
        half_rect = functools.partial(
            half_step_allgather,
            lam=config.lam,
            solver=config.solver,
            table_dtype=config.table_dtype,
        )
    else:
        half_rect = functools.partial(
            half_step_ring,
            lam=config.lam,
            num_shards=config.num_shards,
            solver=config.solver,
            overlap=config.overlap,
            probe=ring_probe,
            fused_epilogue=config.fused_epilogue,
            health=health_probe,
            reg_solve_algo=config.reg_solve_algo,
            table_dtype=config.table_dtype,
        )

    # Factors are exchanged/stored in config.dtype (bfloat16 halves ICI bytes
    # and HBM); Gram contractions follow the storage dtype — native bf16 MXU
    # passes with float32 accumulation for bf16 factors, full-f32 "highest"
    # for float32 (see ops/solve.py _gram_compute_dtype).
    def half(fixed_local, blk):
        # Unified HBM budget → entities per chunk, derived from THIS
        # side's rectangle width (static inside the traced shard).
        return half_rect(
            fixed_local, blk["neighbor"], blk["rating"], blk["mask"],
            blk["count"],
            solve_chunk=config.padded_solve_chunk(blk["neighbor"].shape[-1]),
        )

    if config.exchange == "all_gather":
        half = flagged(half)
    return wrap_step(mesh, config, half, half, mspecs, uspecs,
                     ring_flags=health_probe)


def validate_sharded_dataset(dataset: Dataset, config: ALSConfig, mesh: Mesh) -> None:
    """Catch layout mistakes with actionable errors before XLA sees them."""
    s = config.num_shards
    if mesh.devices.size != s:
        raise ValueError(
            f"mesh has {mesh.devices.size} devices, config.num_shards={s}"
        )
    for name, blocks in (("movie", dataset.movie_blocks), ("user", dataset.user_blocks)):
        if blocks.padded_entities % s != 0:
            raise ValueError(
                f"{name}_blocks padded to {blocks.padded_entities} entities, not "
                f"divisible by num_shards={s}; rebuild the Dataset with "
                f"Dataset.from_coo(..., num_shards={s})"
            )
        if isinstance(blocks, (BucketedBlocks, SegmentBlocks, TiledBlocks)) and blocks.num_shards != s:
            layout = ("bucketed" if isinstance(blocks, BucketedBlocks)
                      else "segment" if isinstance(blocks, SegmentBlocks)
                      else "tiled")
            raise ValueError(
                f"{name}_blocks were built for num_shards={blocks.num_shards} "
                f"but config.num_shards={s}; their row/segment indices are "
                f"shard-local, so rebuild with Dataset.from_coo(..., "
                f"num_shards={s}, layout='{layout}')"
            )


def _config_under_plan(config, exec_plan):
    """The config the sharded step builders should execute: the plan's
    ``half_step_kwargs`` written back over the knob fields.  For
    pinned/default configs the sentinels round-trip to the exact same
    values (bit-identical routing); a cache-hit autotune plan's free-knob
    choices thread like the single-device trainers' seam."""
    import dataclasses as _dc

    kn = exec_plan.half_step_kwargs(config)
    return _dc.replace(
        config,
        overlap=(config.overlap if kn["overlap"] is None
                 else bool(kn["overlap"])),
        fused_epilogue=kn["fused_epilogue"],
        in_kernel_gather=kn["in_kernel_gather"],
        reg_solve_algo=kn["reg_solve_algo"],
        solver=kn["solver"],
        table_dtype=kn["table_dtype"],
    )


def _snapshot_to_host(u, m, **attrs):
    """Allgather-to-host under a ``train/host_gather`` span — the
    expensive host-side edge of a sharded save/snapshot cadence.  The
    resilient loop's ``snapshot_fn`` seam calls it bare; ``save_fn``
    passes ``what="save"``/``i=`` attrs."""
    from cfk_tpu.telemetry import span as _span

    attrs.setdefault("what", "snapshot")
    with _span("train/host_gather", **attrs):
        return to_host(u), to_host(m)


def _sharded_resilient_loop(
    manager, *, model, dataset, config, mesh, dtype, init_fn, make_raw_step,
    mtree, utree, metrics, checkpoint_every, health, fault_injector,
    resume_fn, save_meta, preemption_guard=None, watchdog=None,
    plan_provenance=None,
):
    """Bind the resilient loop's device↔host boundary to a 1-D mesh.

    Shared by the explicit and implicit sharded trainers: snapshots
    process_allgather to host, restores re-shard rows, saves are
    process-0-gated (the gather runs on every process — the collectives
    must pair up — but only rank 0 touches the store, async via the
    manager's writer thread), and escalation overrides rebuild the jitted
    step from a ``dataclasses.replace``d config (λ bump / split epilogue
    are jit-statics, so each rung re-traces).  ``preemption_guard`` /
    ``watchdog`` thread straight into the resilient loop: every process
    polls the guard at the same iteration boundary so the emergency save's
    gather collectives stay in lockstep, and rank 0 writes the manifest.
    """
    import dataclasses as _dc

    from cfk_tpu.resilience.loop import resilient_train_loop, save_checkpoint
    from cfk_tpu.resilience.policy import Overrides, policy_from_config

    def make_step(ov):
        cfg = config
        want = (ov.lam, ov.fused_epilogue,
                ov.reg_solve_algo or config.reg_solve_algo)
        if want != (config.lam, config.fused_epilogue,
                    config.reg_solve_algo):
            cfg = _dc.replace(
                config, lam=ov.lam, fused_epilogue=ov.fused_epilogue,
                reg_solve_algo=ov.reg_solve_algo or config.reg_solve_algo,
            )
        step = jax.jit(make_raw_step(cfg), donate_argnums=(0, 1))
        return lambda u, m: step(u, m, mtree, utree)

    def restore_fn(hu, hm):
        return (
            shard_rows(mesh, np.asarray(hu).astype(dtype)),
            shard_rows(mesh, np.asarray(hm).astype(dtype)),
        )

    def save_fn(done, u, m):
        # Multi-process: every host gathers (cheap, factors are [E, k])
        # but only process 0 writes the checkpoint dir — async, so the
        # step loop never waits for serialize+fsync+rename.  The gathered
        # pair doubles as the resilient loop's rollback anchor.
        uh, mh = _snapshot_to_host(u, m, i=done, what="save")
        if jax.process_index() == 0:
            meta = save_meta
            if plan_provenance is not None:
                # Re-read per save so mid-run plan transitions (rungs,
                # backend outages) appear in subsequent manifests.
                meta = dict(save_meta, **plan_provenance.as_meta())
            save_checkpoint(manager, done, uh, mh, meta=meta)
        return uh, mh

    # Eviction must be a fleet-wide agreement: SIGTERM delivery is racy
    # against iteration boundaries, so each boundary allgather-maxes the
    # per-process flags — any signalled process makes EVERY process run
    # the emergency save at that same boundary.  Only armed (and only a
    # collective) when a guard exists; symmetric across processes because
    # every worker passes the same arguments.
    evict_sync_fn = None
    if preemption_guard is not None and jax.process_count() > 1:
        from jax.experimental import multihost_utils as _mh

        def evict_sync_fn(local: bool) -> bool:
            flags = _mh.process_allgather(
                np.asarray(1 if local else 0, np.int32)
            )
            return bool(np.max(np.asarray(flags)) > 0)

    return resilient_train_loop(
        manager,
        model=model,
        rank=config.rank,
        num_iterations=config.num_iterations,
        u_shape=(dataset.user_blocks.padded_entities, config.rank),
        m_shape=(dataset.movie_blocks.padded_entities, config.rank),
        dtype=dtype,
        init_fn=init_fn,
        make_step=make_step,
        base_overrides=Overrides(
            lam=config.lam, fused_epilogue=config.fused_epilogue
        ),
        metrics=metrics,
        checkpoint_every=checkpoint_every,
        health=health,
        policy=policy_from_config(config),
        fault_injector=fault_injector,
        snapshot_fn=_snapshot_to_host,
        restore_fn=restore_fn,
        save_fn=save_fn,
        resume_fn=resume_fn,
        num_shards=config.num_shards,
        preemption_guard=preemption_guard,
        watchdog=watchdog,
        evict_sync_fn=evict_sync_fn,
        plan_provenance=plan_provenance,
    )


def train_als_sharded(
    dataset: Dataset,
    config: ALSConfig,
    mesh: Mesh,
    *,
    checkpoint_manager=None,
    checkpoint_every: int = 1,
    metrics=None,
    fault_injector=None,
    preemption_guard=None,
    watchdog=None,
) -> ALSModel:
    """Multi-device ALS-WR over a 1-D mesh; semantics match ``train_als``.

    With a ``CheckpointManager``, factors are saved every ``checkpoint_every``
    completed iterations and training resumes from the latest step on restart
    (the explicit form of the reference's never-read per-iteration topic
    journal — SURVEY.md §5 checkpoint/resume).  ``config.health_check_every``
    arms the sentinel: the factor probe is fetched on its cadence and the
    ring half-steps fold per-rotation exchange checks into their carries
    (``make_training_step(health_probe=True)``); a trip rolls back to the
    last good checkpoint and escalates (``cfk_tpu.resilience``).
    """
    from cfk_tpu.config import apply_overlap_xla_flags, enable_compile_cache
    from cfk_tpu.resilience.loop import validate_cadence
    from cfk_tpu.resilience.sentinel import health_from_config

    from cfk_tpu.plan import plan_for_config

    # Before the first compile (ISSUE 13): warm-start compile caching.
    enable_compile_cache(getattr(config, "compile_cache_dir", None))
    s = config.num_shards
    health = health_from_config(config)
    validate_cadence(checkpoint_every, health)
    apply_overlap_xla_flags(config)
    validate_sharded_dataset(dataset, config, mesh)
    exec_plan, plan_prov = plan_for_config(
        config,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
        nnz=max(int(dataset.movie_blocks.count.sum()), 1),
    )
    # The sharded step builders read knobs off the config object, so the
    # plan seam is applied by rebuilding the config from the plan's
    # half_step_kwargs — identical for pinned/default configs (the
    # sentinels round-trip), and the manifest provenance can never attest
    # to a plan the execution ignored.
    config = _config_under_plan(config, exec_plan)

    if exec_plan.offload_tier == "host_window":
        # Out-of-core tier, sharded (ISSUE 12): the per-shard budget
        # predicate said resident tables cannot fit one device (or the
        # config pinned the tier) — training runs through the sharded
        # windowed host-offload driver, bit-exact vs THIS resident path
        # (per-shard staged windows under the all_gather scan or the
        # ring/hier_ring visit schedules; tests/test_offload_sharded.py).
        unsupported = [
            name for name, v in (
                ("checkpoint_manager", checkpoint_manager),
                ("fault_injector", fault_injector),
                ("preemption_guard", preemption_guard),
                ("watchdog", watchdog),
            ) if v is not None
        ]
        if unsupported:
            raise NotImplementedError(
                f"offload_tier='host_window' does not support "
                f"{unsupported} yet — the windowed driver keeps factors "
                "in host stores (see cfk_tpu/offload/windowed.py; "
                "window-level fault injection uses its window_faults=)"
            )
        from cfk_tpu.offload.windowed import train_als_host_window
        from cfk_tpu.utils.metrics import Metrics as _Metrics

        metrics = metrics if metrics is not None else _Metrics()
        metrics.note("plan", plan_prov.summary())
        # Config-threading ≡ half_step_kwargs for the windowed driver:
        # _config_under_plan already wrote the plan's knobs back over the
        # config fields, so execution cannot diverge from the provenance.
        return train_als_host_window(
            dataset, config, metrics=metrics, plan_provenance=plan_prov,
        )

    gathered = gathered_layout_trees(dataset, config)
    stats_init = gathered is not None  # bucketed/segment: init from stats
    step_kw = {}
    if gathered is not None:
        mtree, utree, step_kw = gathered
    elif config.exchange == "all_gather":
        mtree = _padded_to_tree(dataset.movie_blocks)
        utree = _padded_to_tree(dataset.user_blocks)
    else:
        coo = dataset.coo_dense
        mtree = _ring_to_tree(
            build_ring_blocks(
                coo.movie_raw, coo.user_raw, coo.rating,
                dataset.movie_map.num_entities, dataset.user_map.num_entities,
                num_shards=s, pad_multiple=config.pad_multiple,
            )
        )
        utree = _ring_to_tree(
            build_ring_blocks(
                coo.user_raw, coo.movie_raw, coo.rating,
                dataset.user_map.num_entities, dataset.movie_map.num_entities,
                num_shards=s, pad_multiple=config.pad_multiple,
            )
        )

    mtree = shard_rows(mesh, mtree)
    utree = shard_rows(mesh, utree)

    from cfk_tpu.transport.checkpoint import resume_state_synced

    dtype = jnp.dtype(config.dtype)

    def init_fn():
        # Init outside shard_map, drawn at the REAL entity count (threefry
        # output depends on the draw shape, so drawing at the shard-count-
        # padded length would make the init a function of num_shards — the
        # old 4-shard tiled mismatch); pad rows are zero either way.
        key = jax.random.PRNGKey(config.seed)
        init_kw = dict(
            rank=config.rank,
            num_entities=dataset.user_blocks.num_entities,
        )
        if stats_init:
            u = jax.jit(
                init_factors_stats, static_argnames=("rank", "num_entities")
            )(
                key,
                jnp.asarray(dataset.user_blocks.rating_sum),
                jnp.asarray(dataset.user_blocks.count),
                **init_kw,
            ).astype(dtype)
        else:
            u = jax.jit(
                init_factors, static_argnames=("rank", "num_entities")
            )(
                key,
                jnp.asarray(dataset.user_blocks.rating),
                jnp.asarray(dataset.user_blocks.mask),
                jnp.asarray(dataset.user_blocks.count),
                **init_kw,
            ).astype(dtype)
        u = shard_rows(mesh, u)
        m = shard_rows(
            mesh,
            np.zeros((dataset.movie_blocks.padded_entities, config.rank), dtype),
        )
        return u, m

    from cfk_tpu.utils.metrics import Metrics

    metrics = metrics if metrics is not None else Metrics()
    metrics.note("plan", plan_prov.summary())
    u, m = _sharded_resilient_loop(
        checkpoint_manager,
        model="als",
        dataset=dataset,
        config=config,
        mesh=mesh,
        dtype=dtype,
        init_fn=init_fn,
        make_raw_step=lambda cfg: make_training_step(
            mesh, cfg, _tree_specs(mtree), _tree_specs(utree),
            health_probe=health is not None, **step_kw
        ),
        mtree=mtree,
        utree=utree,
        metrics=metrics,
        checkpoint_every=checkpoint_every,
        health=health,
        fault_injector=fault_injector,
        preemption_guard=preemption_guard,
        watchdog=watchdog,
        resume_fn=lambda: resume_state_synced(
            checkpoint_manager,
            rank=config.rank,
            model="als",
            num_iterations=config.num_iterations,
            u_shape=(dataset.user_blocks.padded_entities, config.rank),
            m_shape=(dataset.movie_blocks.padded_entities, config.rank),
            num_shards=config.num_shards,
        ),
        save_meta={
            "rank": config.rank,
            "exchange": config.exchange,
            "model": "als",
            "num_shards": config.num_shards,
        },
        plan_provenance=plan_prov,
    )

    return ALSModel(
        user_factors=u,
        movie_factors=m,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )


# -- item-axis sharded top-K serving (ISSUE 8) -------------------------------

def serve_topk_sharded(
    mesh: Mesh,
    u,  # [B, k] user-factor batch (replicated)
    table,  # [M_pad, k] item table, M_pad a multiple of shards·tile_m
    scale,  # [M_pad] f32 int8 per-row scales, or None
    seen_tiles,  # [NT, B, W] int32 (serving.topk_kernel.build_seen_tiles)
    *,
    k_top: int,
    num_movies: int,
    tile_m: int = 512,
):
    """Item-axis sharded score+top-K: (scores [B, K], movie rows [B, K]).

    The serving analog of the half-steps' exchange, with the direction
    reversed: the ITEM table is row-sharded over the mesh, the [B, k]
    request batch is replicated, each shard runs the streaming score+top-K
    kernel over its own table slice (its global row base rides the
    kernel's scalar-prefetched ``row_offset``), and ONE all_gather of the
    per-shard [B, K] selections — [B, shards·K] — feeds a final
    ``lax.top_k`` merge.  No dense score block ever crosses a shard
    boundary; the exchange is O(B·shards·K), independent of num_movies.

    Bit-equality with the single-shard kernel holds by construction:
    per-element score dots are identical (same k-order contraction), and
    the merge concatenates shards in ring order = ascending global tile
    order, which is exactly the order the single-shard carry folds tiles —
    so ties resolve identically (``tests/test_serving.py`` pins
    multi-shard == single-shard bit-exactly).
    """
    shards = mesh.devices.size
    m_pad = table.shape[0]
    if m_pad % (shards * tile_m) != 0:
        raise ValueError(
            f"table rows {m_pad} not divisible by shards×tile_m "
            f"({shards}×{tile_m}); pad with serving.engine.pad_table"
        )
    nt = m_pad // tile_m
    if nt % shards != 0:  # pragma: no cover - implied by the check above
        raise ValueError(f"{nt} tiles not divisible by {shards} shards")

    # int8 scales / seen rectangles shard with the table rows / tiles; a
    # zero placeholder keeps the spec arity fixed when absent.
    sc_op = (jnp.zeros((m_pad,), jnp.float32) if scale is None
             else scale.astype(jnp.float32))
    seen_op = (jnp.zeros((nt, u.shape[0], 1), jnp.int32)
               if seen_tiles is None else seen_tiles)
    fn = _serve_topk_sharded_fn(
        mesh, m_pad // shards, scale is not None, seen_tiles is not None,
        k_top, num_movies, tile_m,
    )
    return fn(u, table, sc_op, seen_op)


@functools.lru_cache(maxsize=64)
def _serve_topk_sharded_fn(mesh, rows_per_shard, has_scale, has_seen,
                           k_top, num_movies, tile_m):
    """Jitted shard_map for one (mesh, shapes-class, K) serving config —
    cached so a live server's request stream reuses compiled programs
    instead of re-tracing the shard_map per call (the engine's pow2
    bucketing keeps the distinct key count small)."""
    from cfk_tpu.serving.topk_kernel import topk_scores_pallas

    def shard_fn(u_rep, tbl, sc, seen):
        off = lax.axis_index(AXIS).astype(jnp.int32) * rows_per_shard
        v, ids = topk_scores_pallas(
            u_rep, tbl, sc if has_scale else None,
            seen if has_seen else None,
            k_top=k_top, num_movies=num_movies, tile_m=tile_m,
            row_offset=off,
        )
        cat_v = lax.all_gather(v, AXIS, axis=1, tiled=True)
        cat_i = lax.all_gather(ids, AXIS, axis=1, tiled=True)
        mv, pos = lax.top_k(cat_v, k_top)
        return mv, jnp.take_along_axis(cat_i, pos, axis=1)

    return jax.jit(_compat_shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(), P()),
    ))
