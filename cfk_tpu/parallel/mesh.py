"""Device mesh construction + sharding helpers.

The reference's distribution fabric is N Kafka partitions with deterministic
mod-N partitioners keeping state-store locality aligned with topic partitions
(``producers/PureModPartitioner.java:17``, SURVEY.md §2.6).  Here the fabric
is a 1-D ``jax.sharding.Mesh`` over the ``"shard"`` axis: entity rows are
contiguously block-sharded over devices, and all cross-device traffic is XLA
collectives over ICI (all_gather / ppermute), not message passing.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "shard"


def make_mesh(num_shards: int, devices: list | None = None) -> Mesh:
    """A 1-D mesh of ``num_shards`` devices on the ``"shard"`` axis."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < num_shards:
        raise ValueError(
            f"need {num_shards} devices, have {len(devices)} "
            f"({[d.platform for d in devices[:3]]}...)"
        )
    return Mesh(np.array(devices[:num_shards]), (AXIS,))


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Idempotent ``jax.distributed.initialize`` wrapper for multi-host runs.

    The reference scales out by adding Kafka partitions consumed by more
    stream threads/processes against one broker (``apps/BaseKafkaApp.java:51``
    — never actually run multi-node, SURVEY.md §4).  Here multi-host is JAX's
    single-program-multiple-controller model: every host runs this same
    program, this call wires them into one runtime, and the ``"shard"`` axis
    then spans all hosts' devices — collectives ride ICI within a host/slice
    and DCN across.  Returns the number of processes.

    MUST be the first JAX call of the program when ``coordinator_address`` is
    given: ``jax.distributed.initialize`` refuses to run once any XLA backend
    exists (even ``jax.devices()`` initializes one).  Calling again after a
    successful multi-process init is a no-op; calling too late with a
    mismatching topology raises.
    """
    if coordinator_address is None:
        return jax.process_count()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError:
        # Backend already up (or initialize called twice).  Fine iff the
        # runtime already has the topology the caller asked for.
        if num_processes is not None and jax.process_count() != num_processes:
            raise
    return jax.process_count()


def ring_order(devices):
    """Order devices so contiguous ranges are intra-host (ICI-first).

    Sorting key (process_index, device id): neighbor shards on the ring and
    contiguous all_gather ranges then sit on the same host wherever possible,
    so the ppermute ring crosses DCN only at host boundaries and XLA can
    lower all_gather hierarchically (ICI within host, DCN across).
    """
    return sorted(devices, key=lambda d: (d.process_index, d.id))


def make_multihost_mesh(num_shards: int | None = None) -> Mesh:
    """A 1-D ``"shard"`` mesh spanning every device of every process.

    The 1-D entity axis is the whole parallelism of block ALS (factors and
    blocks are row-sharded; there is no separate data/model axis to fold), so
    multi-host just extends the axis across hosts in ``ring_order``.
    """
    devices = ring_order(jax.devices())
    if num_shards is None:
        num_shards = len(devices)
    if len(devices) != num_shards:
        raise ValueError(
            f"num_shards={num_shards} must equal the global device count "
            f"{len(devices)} for a multihost mesh (every device hosts one "
            "entity shard); build the Dataset with this num_shards"
        )
    return Mesh(np.array(devices), (AXIS,))


def shard_rows_global(mesh: Mesh, tree):
    """Multi-host-safe row sharding: assemble global arrays per-shard.

    Unlike ``shard_rows`` (single-controller ``device_put``), this works under
    multi-process JAX where each host may only address its local devices: each
    process materializes only the row slices its devices own, via
    ``jax.make_array_from_callback``.  The input tree holds the full global
    (host/numpy) arrays on every process — fine for rating blocks, whose host
    copy exists anyway.
    """
    def put(x):
        spec = P(AXIS, *([None] * (np.ndim(x) - 1)))
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            np.shape(x), sharding, lambda idx: np.asarray(x)[idx]
        )

    return jax.tree.map(put, tree)


def shard_rows(mesh: Mesh, tree):
    """Place a pytree of arrays with axis 0 sharded over the mesh.

    Under multi-process JAX (``jax.process_count() > 1``) a single-controller
    ``device_put`` cannot address remote hosts' devices, so this routes to
    ``shard_rows_global`` — every trainer call site stays topology-agnostic.
    """
    if jax.process_count() > 1:
        return shard_rows_global(mesh, tree)

    def put(x):
        spec = P(AXIS, *([None] * (np.ndim(x) - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, tree)


def to_host(x) -> np.ndarray:
    """Fetch an array to host numpy, gathering across processes if needed.

    Single-process (or fully-addressable) arrays fetch directly; a
    multi-process row-sharded global array is ``process_allgather``'d so
    every host returns the same full matrix (factors are small — [E, k]).
    """
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def replicated(mesh: Mesh, tree):
    """Place a pytree fully replicated over the mesh."""
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree
    )
