"""Device mesh construction + sharding helpers.

The reference's distribution fabric is N Kafka partitions with deterministic
mod-N partitioners keeping state-store locality aligned with topic partitions
(``producers/PureModPartitioner.java:17``, SURVEY.md §2.6).  Here the fabric
is a 1-D ``jax.sharding.Mesh`` over the ``"shard"`` axis: entity rows are
contiguously block-sharded over devices, and all cross-device traffic is XLA
collectives over ICI (all_gather / ppermute), not message passing.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "shard"


def make_mesh(num_shards: int, devices: list | None = None) -> Mesh:
    """A 1-D mesh of ``num_shards`` devices on the ``"shard"`` axis."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < num_shards:
        raise ValueError(
            f"need {num_shards} devices, have {len(devices)} "
            f"({[d.platform for d in devices[:3]]}...)"
        )
    return Mesh(np.array(devices[:num_shards]), (AXIS,))


def shard_rows(mesh: Mesh, tree):
    """Place a pytree of arrays with axis 0 sharded over the mesh."""
    def put(x):
        spec = P(AXIS, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, tree)


def replicated(mesh: Mesh, tree):
    """Place a pytree fully replicated over the mesh."""
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree
    )
