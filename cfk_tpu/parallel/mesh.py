"""Device mesh construction + sharding helpers.

The reference's distribution fabric is N Kafka partitions with deterministic
mod-N partitioners keeping state-store locality aligned with topic partitions
(``producers/PureModPartitioner.java:17``, SURVEY.md §2.6).  Here the fabric
is a 1-D ``jax.sharding.Mesh`` over the ``"shard"`` axis: entity rows are
contiguously block-sharded over devices, and all cross-device traffic is XLA
collectives over ICI (all_gather / ppermute), not message passing.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "shard"


def make_mesh(num_shards: int, devices: list | None = None) -> Mesh:
    """A 1-D mesh of ``num_shards`` devices on the ``"shard"`` axis."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < num_shards:
        raise ValueError(
            f"need {num_shards} devices, have {len(devices)} "
            f"({[d.platform for d in devices[:3]]}...)"
        )
    return Mesh(np.array(devices[:num_shards]), (AXIS,))


# Exit status of the init-timeout watchdog below: "the fleet never
# assembled within the bound" — distinct from crash codes so supervisors
# and the test drills can tell it from a wreck.
INIT_TIMEOUT_EXIT_CODE = 18


def _looks_like_init_timeout(e: BaseException) -> bool:
    # ONLY the init-barrier deadline signature (measured: "absl::Status:
    # DEADLINE_EXCEEDED ... RegisterTask").  A generic "timeout" substring
    # match would rewrite unrelated coordination errors (heartbeat/barrier
    # failures, a second initialize call) into a misleading "fleet never
    # assembled" diagnosis and bypass the topology check below.
    msg = str(e).lower()
    return "deadline_exceeded" in msg or "deadline exceeded" in msg


def _init_timeout_message(coordinator_address, num_processes, process_id,
                          timeout_s) -> str:
    missing = (
        sorted(set(range(num_processes)) - {process_id})
        if num_processes is not None and process_id is not None
        else "unknown"
    )
    return (
        f"initialize_distributed timed out after {timeout_s}s: process "
        f"{process_id} waited at coordinator {coordinator_address} but the "
        f"runtime never assembled all {num_processes} processes — the "
        f"missing peer(s) are among process ids {missing}; check that every "
        "process was launched, is still alive, and can reach the "
        "coordinator"
    )


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    init_timeout_s: float | None = None,
) -> int:
    """Idempotent ``jax.distributed.initialize`` wrapper for multi-host runs.

    The reference scales out by adding Kafka partitions consumed by more
    stream threads/processes against one broker (``apps/BaseKafkaApp.java:51``
    — never actually run multi-node, SURVEY.md §4).  Here multi-host is JAX's
    single-program-multiple-controller model: every host runs this same
    program, this call wires them into one runtime, and the ``"shard"`` axis
    then spans all hosts' devices — collectives ride ICI within a host/slice
    and DCN across.  Returns the number of processes.

    MUST be the first JAX call of the program when ``coordinator_address`` is
    given: ``jax.distributed.initialize`` refuses to run once any XLA backend
    exists (even ``jax.devices()`` initializes one).  Calling again after a
    successful multi-process init is a no-op; calling too late with a
    mismatching topology raises.

    ``init_timeout_s`` bounds how long this process waits at the startup
    barrier for its peers (the runtime default is 300 s of silent hanging).
    The installed runtime never surfaces that expiry as a catchable Python
    exception — XLA's distributed client ABORTS the process from an error
    callback (``client.h:80 F ... DEADLINE_EXCEEDED``, measured on jax
    0.4.37) with a message that names no peer.  So the bound is enforced
    here: a watchdog thread fires ``init_timeout_s`` BEFORE the runtime's
    own (longer) deadline, prints an actionable diagnosis naming this
    process, the coordinator address, and the candidate missing process
    ids, and exits ``INIT_TIMEOUT_EXIT_CODE``.  On runtimes that do raise
    a catchable deadline error, the same diagnosis rides a ``TimeoutError``
    instead.
    """
    if coordinator_address is None:
        return jax.process_count()
    kw = {}
    watchdog_done = None
    if init_timeout_s is not None:
        import os as _os
        import sys as _sys
        import threading

        # Give the runtime's own deadline headroom past ours so the
        # actionable watchdog always wins the race against the bare
        # absl-fatal abort.
        kw["initialization_timeout"] = int(max(1, init_timeout_s)) + 15
        watchdog_done = threading.Event()

        def _watch():
            if watchdog_done.wait(init_timeout_s):
                return
            print(
                _init_timeout_message(
                    coordinator_address, num_processes, process_id,
                    init_timeout_s,
                ),
                file=_sys.stderr,
                flush=True,
            )
            _os._exit(INIT_TIMEOUT_EXIT_CODE)

        threading.Thread(
            target=_watch, name="cfk-init-timeout", daemon=True
        ).start()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kw,
        )
    except RuntimeError as e:
        if watchdog_done is not None:
            watchdog_done.set()
        if _looks_like_init_timeout(e):
            raise TimeoutError(
                _init_timeout_message(
                    coordinator_address, num_processes, process_id,
                    init_timeout_s if init_timeout_s is not None else 300,
                )
            ) from e
        # Backend already up (or initialize called twice).  Fine iff the
        # runtime already has the topology the caller asked for.
        if num_processes is not None and jax.process_count() != num_processes:
            raise
    finally:
        if watchdog_done is not None:
            watchdog_done.set()
    return jax.process_count()


def ring_order(devices):
    """Order devices so contiguous ranges are intra-host (ICI-first).

    Sorting key (process_index, device id): neighbor shards on the ring and
    contiguous all_gather ranges then sit on the same host wherever possible,
    so the ppermute ring crosses DCN only at host boundaries and XLA can
    lower all_gather hierarchically (ICI within host, DCN across).
    """
    return sorted(devices, key=lambda d: (d.process_index, d.id))


def make_multihost_mesh(num_shards: int | None = None) -> Mesh:
    """A 1-D ``"shard"`` mesh spanning every device of every process.

    The 1-D entity axis is the whole parallelism of block ALS (factors and
    blocks are row-sharded; there is no separate data/model axis to fold), so
    multi-host just extends the axis across hosts in ``ring_order``.
    """
    devices = ring_order(jax.devices())
    if num_shards is None:
        num_shards = len(devices)
    if len(devices) != num_shards:
        raise ValueError(
            f"num_shards={num_shards} must equal the global device count "
            f"{len(devices)} for a multihost mesh (every device hosts one "
            "entity shard); build the Dataset with this num_shards"
        )
    return Mesh(np.array(devices), (AXIS,))


def shard_rows_global(mesh: Mesh, tree):
    """Multi-host-safe row sharding: assemble global arrays per-shard.

    Unlike ``shard_rows`` (single-controller ``device_put``), this works under
    multi-process JAX where each host may only address its local devices: each
    process materializes only the row slices its devices own, via
    ``jax.make_array_from_callback``.  The input tree holds the full global
    (host/numpy) arrays on every process — fine for rating blocks, whose host
    copy exists anyway.
    """
    def put(x):
        spec = P(AXIS, *([None] * (np.ndim(x) - 1)))
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            np.shape(x), sharding, lambda idx: np.asarray(x)[idx]
        )

    return jax.tree.map(put, tree)


def shard_rows(mesh: Mesh, tree):
    """Place a pytree of arrays with axis 0 sharded over the mesh.

    Under multi-process JAX (``jax.process_count() > 1``) a single-controller
    ``device_put`` cannot address remote hosts' devices, so this routes to
    ``shard_rows_global`` — every trainer call site stays topology-agnostic.
    """
    if jax.process_count() > 1:
        return shard_rows_global(mesh, tree)

    def put(x):
        spec = P(AXIS, *([None] * (np.ndim(x) - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, tree)


def to_host(x) -> np.ndarray:
    """Fetch an array to host numpy, gathering across processes if needed.

    Single-process (or fully-addressable) arrays fetch directly; a
    multi-process row-sharded global array is ``process_allgather``'d so
    every host returns the same full matrix (factors are small — [E, k]).
    """
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def replicated(mesh: Mesh, tree):
    """Place a pytree fully replicated over the mesh."""
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree
    )
