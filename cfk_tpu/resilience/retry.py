"""Exponential backoff + jitter: the one retry schedule the framework uses.

Replaces ad-hoc fixed-interval polls (the broker-spawn wait loop's
``time.sleep(0.05)``) and gives the TCP client's connect/read paths a
bounded, jittered schedule instead of hammering a recovering broker at a
fixed frequency (thundering-herd on restart is exactly how a half-healthy
broker stays half-healthy).
"""

from __future__ import annotations

import random
import time
from typing import Iterator


def backoff_delays(
    base: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    rng: random.Random | None = None,
) -> Iterator[float]:
    """Infinite stream of sleep intervals: ``base·factor^n`` capped at
    ``max_delay``, each scaled by a uniform jitter in
    ``[1-jitter, 1+jitter]``.  Pass a seeded ``rng`` for deterministic
    schedules (the fault-injection tests do)."""
    if base <= 0:
        raise ValueError(f"base must be > 0, got {base}")
    if not 0 <= jitter < 1:
        raise ValueError(f"jitter must be in [0, 1), got {jitter}")
    rng = rng or random
    delay = base
    while True:
        yield delay * (1.0 + jitter * (2.0 * rng.random() - 1.0))
        delay = min(delay * factor, max_delay)


def retry_call(
    fn,
    *,
    retries: int = 3,
    retry_on: tuple = (OSError,),
    base: float = 0.05,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    rng: random.Random | None = None,
    sleep=time.sleep,
    describe: str = "operation",
):
    """Call ``fn()`` with up to ``retries`` backed-off retries on
    ``retry_on`` exceptions; the final failure re-raises the last error.
    ``sleep`` is injectable so tests assert the schedule without waiting.
    """
    delays = backoff_delays(
        base=base, max_delay=max_delay, jitter=jitter, rng=rng
    )
    last: BaseException | None = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except retry_on as e:
            last = e
            # Flight-record each retried failure: a flaky broker's
            # drop/backoff timeline is the forensic trail chaos_lab's
            # flaky_broker scenario asserts on.
            from cfk_tpu.telemetry.recorder import record_event

            record_event("retry", "retryable_failure", op=describe,
                         attempt=attempt + 1,
                         error=f"{type(e).__name__}: {e}")
            if attempt == retries:
                break
            sleep(next(delays))
    msg = f"{describe} failed after {retries + 1} attempts: {last}"
    # Wrap with the attempts context while keeping the original type AND
    # its errno (callers branch on e.errno); exception classes whose
    # constructors cannot take one message re-raise the original rather
    # than masking it with a TypeError.
    if isinstance(last, OSError) and last.errno is not None:
        wrapped = type(last)(last.errno, msg)
    else:
        try:
            wrapped = type(last)(msg)
        except TypeError:
            raise last
    raise wrapped from last
