"""The resilient stepped training loop every trainer shares.

One loop, four call sites (explicit/implicit × single-device/SPMD): step
from Python, journal factors on the checkpoint cadence, evaluate the
health sentinel on its cadence, and on a trip roll back to the last good
state and climb the escalation ladder (``cfk_tpu.resilience.policy``)
before retrying — bounded, then gracefully degrading to last-good factors
plus a diagnostic report instead of crashing.

With ``health=None``, no policy and no injector this reduces exactly to
the pre-resilience checkpointed loop (``transport.checkpoint.
checkpointed_train_loop`` delegates here), so save cadence / resume
validation / metrics accounting stay identical across model families by
construction.

The SPMD trainers parameterize the device↔host boundary via
``snapshot_fn``/``restore_fn``/``save_fn``/``resume_fn`` (host gather is a
``process_allgather``, restore re-shards, saves are process-0-gated);
single-device callers take the numpy defaults.  Under multi-process JAX
the probe word is a fully-replicated scalar, so every process fetches the
same value and takes the same rollback decision in lockstep — no extra
broadcast needed.
"""

from __future__ import annotations

import functools
import warnings

import numpy as np

from cfk_tpu.resilience import sentinel as _sentinel
from cfk_tpu.resilience.policy import (
    Overrides,
    RecoveryPolicy,
    TrainingDivergedError,
)
from cfk_tpu.telemetry import record_event, span
from cfk_tpu.telemetry.recorder import dump_flight


def validate_cadence(checkpoint_every: int, health=None) -> None:
    """Actionable validation of the loop cadences (satellite of ISSUE 3).

    ``checkpoint_every < 1`` used to surface only from ``should_save``
    deep inside the first iteration; a non-positive health cadence would
    silently never probe (``done % every`` can never hit 0 for every <= 0
    before Python raises on the modulo by zero mid-run).
    """
    if checkpoint_every < 1:
        raise ValueError(
            f"checkpoint_every must be >= 1 (iterations between factor "
            f"saves), got {checkpoint_every}; use checkpoint_every=1 for "
            "per-iteration journaling or a larger value to save less often"
        )
    if health is not None and health.every < 1:
        raise ValueError(
            f"health_check_every must be >= 1 (iterations between sentinel "
            f"probes), got {health.every}; use health_check_every=None to "
            "disable the sentinel entirely"
        )


def save_checkpoint(manager, done, hu, hm, *, meta=None):
    """One save-point write: async when the manager supports it.

    ``CheckpointManager.save_async`` backgrounds the serialize + fsync +
    atomic rename on its writer thread so the step loop never idles behind
    disk; stores without an async writer (``JournalCheckpointManager``,
    chaos wrappers that pin the sync path) fall back to a blocking save.
    """
    if hasattr(manager, "save_async"):
        manager.save_async(done, hu, hm, meta=meta)
    else:
        manager.save(done, hu, hm, meta=meta)


def drain_checkpoints(manager) -> None:
    """Barrier on the async checkpoint writer (no-op for sync stores).

    Called before every rollback read and at every loop exit so readers —
    the rollback path, the caller's post-training ``restore()``, the next
    process after a preemption — only ever observe committed steps; the
    crc32/torn-step verification contract is unchanged by async writes.
    """
    if manager is not None and hasattr(manager, "wait_pending"):
        manager.wait_pending()


def resilient_train_loop(
    manager,
    *,
    model: str,
    rank: int,
    num_iterations: int,
    u_shape,
    m_shape,
    dtype,
    init_fn,
    metrics,
    step_fn=None,
    make_step=None,
    base_overrides: Overrides | None = None,
    checkpoint_every: int = 1,
    health: "_sentinel.HealthConfig | None" = None,
    policy: RecoveryPolicy | None = None,
    fault_injector=None,
    snapshot_fn=None,
    restore_fn=None,
    save_fn=None,
    resume_fn=None,
    num_shards: int = 1,
    preemption_guard=None,
    watchdog=None,
    evict_sync_fn=None,
    plan_provenance=None,
):
    """Run the stepped loop; returns the final ``(u, m)`` device factors.

    Exactly one of ``step_fn`` (a fixed ``(u, m) -> (u, m)`` step — no
    escalation possible beyond plain rollback+retry) or ``make_step``
    (``make_step(Overrides) -> step`` — the full ladder) must be given.
    A step may also return ``(u, m, ring_bad)`` where ``ring_bad`` is the
    in-carry ring-exchange probe flag the SPMD ring half-steps emit; it is
    fetched on the health cadence and folded into the probe word.

    ``preemption_guard`` (``cfk_tpu.resilience.preempt.PreemptionGuard``)
    is polled between iterations: once triggered, the loop drains the
    async checkpoint writer, commits a final checkpoint (unless the state
    just failed its health probe) and returns resumable.  ``watchdog``
    (``StallWatchdog``) is armed around the loop and ticked per completed
    iteration — a peer death that wedges a collective then bounds this
    process's exit instead of hanging it forever.

    ``evict_sync_fn(local: bool) -> bool`` makes the eviction decision a
    fleet-wide AGREEMENT under multi-process JAX: signal delivery is
    per-process and racy against iteration boundaries, so acting on the
    local flag alone could have one process run the emergency-save
    collectives at a boundary its peers already left (desync → the
    graceful preemption degrades into a watchdog stall exit).  The
    sharded trainers inject an allgather-max so every process evicts at
    the same boundary as soon as ANY process was signalled; it is a
    collective, so the loop calls it on every iteration whenever it is
    set (the single-process default is the plain local flag).
    """
    import jax.numpy as jnp

    from cfk_tpu.transport.checkpoint import resume_state

    validate_cadence(checkpoint_every, health)
    if (step_fn is None) == (make_step is None):
        raise ValueError("pass exactly one of step_fn / make_step")
    policy = policy or RecoveryPolicy()
    if snapshot_fn is None:
        # SNAPSHOT-BEFORE-DONATE (ISSUE 13 audit): the trainers' step
        # jits DONATE their factor arguments, and on CPU np.asarray of a
        # jax array can be a zero-copy VIEW of the device buffer — a
        # donated step could then reuse the snapshot's memory for its
        # outputs and silently rewrite the ladder's last-good anchor.
        # np.array(copy=True) pins an owned host copy; same bytes.
        snapshot_fn = lambda u, m: (np.array(u, copy=True),
                                    np.array(m, copy=True))
    if restore_fn is None:
        restore_fn = lambda hu, hm: (
            jnp.asarray(hu, dtype=dtype), jnp.asarray(hm, dtype=dtype)
        )
    if save_fn is None:
        def save_fn(done, u, m):
            # Owned copies, not views: the returned pair doubles as the
            # rollback anchor (host_pair) and must survive the next
            # donated step — see snapshot_fn above.
            hu, hm = np.array(u, copy=True), np.array(m, copy=True)
            meta = {"rank": rank, "model": model,
                    "num_shards": num_shards}
            if plan_provenance is not None:
                # Plan provenance rides every manifest (ISSUE 9): which
                # plan trained these factors, why it was chosen, and any
                # mid-run transitions (escalation rungs, backend
                # outages) — re-read at transition time so later rungs
                # appear in later manifests.
                meta.update(plan_provenance.as_meta())
            save_checkpoint(manager, done, hu, hm, meta=meta)
            return hu, hm

    if resume_fn is None:
        resume_fn = functools.partial(
            resume_state, manager, rank=rank, model=model,
            num_iterations=num_iterations, u_shape=u_shape, m_shape=m_shape,
            num_shards=num_shards,
        )
    state = resume_fn()
    if state is not None:
        start_iter = state.iteration
        u, m = restore_fn(state.user_factors, state.movie_factors)
    else:
        start_iter = 0
        u, m = init_fn()

    # The GJ escalation rung is a threaded step-build parameter
    # (Overrides.reg_solve_algo → make_step → the half-steps' algo
    # jit-static), so escalation leaves no process state behind — the
    # CFK_REG_SOLVE_ALGO env var save/restore dance is gone.
    overrides = base_overrides or Overrides(lam=0.0)
    step = step_fn if make_step is None else make_step(overrides)
    probe = None
    if health is not None:
        import jax

        probe = jax.jit(
            lambda u, m: _sentinel.probe_word(u, m, health.norm_limit)
        )
    if watchdog is not None:
        watchdog.arm()
    try:
        return _run_loop_body(
            manager=manager, num_iterations=num_iterations,
            start_iter=start_iter, u=u, m=m, step=step,
            make_step=make_step, overrides=overrides, policy=policy,
            health=health, probe=probe, metrics=metrics,
            checkpoint_every=checkpoint_every,
            fault_injector=fault_injector, snapshot_fn=snapshot_fn,
            restore_fn=restore_fn, save_fn=save_fn, state=state,
            init_fn=init_fn, guard=preemption_guard, watchdog=watchdog,
            evict_sync_fn=evict_sync_fn, plan_provenance=plan_provenance,
        )
    finally:
        if watchdog is not None:
            watchdog.disarm()
        # Loop-exit barrier: every return path (completion, degrade,
        # preemption, or an exception unwinding) leaves only committed
        # steps behind before the caller can read the store.
        drain_checkpoints(manager)


def _run_loop_body(
    *, manager, num_iterations, start_iter, u, m, step, make_step,
    overrides, policy, health, probe, metrics, checkpoint_every,
    fault_injector, snapshot_fn, restore_fn, save_fn, state, init_fn,
    guard=None, watchdog=None, evict_sync_fn=None, plan_provenance=None,
):
    from cfk_tpu.plan import registry as _plan_registry
    from cfk_tpu.transport.checkpoint import should_save

    # Kernel-backend availability generation the current step was BUILT
    # under: if it moves (a backend forced unavailable mid-run — an
    # outage, a chaos drill), the step must be rebuilt on rollback even at
    # escalation rung 1 (plain retry), because a rebuild NOW resolves to
    # different kernels — that rebuild is a plan transition, recorded with
    # the same provenance vocabulary as an escalation rung.
    registry_gen = _plan_registry.generation()

    # Last-good rollback anchor: (iteration, host snapshot).  Updated only
    # at validated save points, so a committed checkpoint and the anchor
    # can never disagree about what "good" means; a trip before the first
    # save point rolls back to a deterministic re-init.
    good: tuple[int, tuple] | None = None
    trips = 0
    reports: list[_sentinel.HealthReport] = []

    def rollback():
        if good is not None:
            it, (hu, hm) = good
            return it, restore_fn(hu, hm)
        return start_iter, _resume_or_init(state, restore_fn, init_fn)

    i = start_iter
    ring_pending = False  # ring-exchange flags seen since the last probe
    while i < num_iterations:
        if fault_injector is not None:
            u, m = fault_injector.before_step(i, u, m)
        with metrics.phase("train"), span("train/iter", i=i):
            out = step(u, m)
            u, m, ring_bad = out if len(out) == 3 else (*out, None)
            u.block_until_ready()
        record_event("train", "iter", i=i)
        if ring_bad is not None:
            # Accumulate EVERY step's exchange flag (a ready int32 scalar
            # — block_until_ready already synced) so a corrupt in-flight
            # block between probes still gets its RING_EXCHANGE
            # attribution at the next probe, at any health cadence.
            ring_pending = ring_pending or int(np.asarray(ring_bad)) > 0
        metrics.incr("iterations")
        done = i + 1
        if watchdog is not None:
            watchdog.tick(done)
        # Eviction poll.  Signal delivery is per-process and racy against
        # iteration boundaries, so multi-process runs AGREE on the flag
        # via evict_sync_fn (an allgather-max the sharded trainers
        # inject): every process then runs the emergency save's
        # host-gather collectives at the same boundary, even when only
        # one process was signalled.
        evicting = guard is not None and guard.triggered
        if evict_sync_fn is not None:
            evicting = bool(evict_sync_fn(evicting))
        # With no checkpoint store there is no commit to protect, so the
        # save cadence must not drive probes or snapshots — the health
        # cadence alone does (checkpoint_every defaults to 1, which would
        # otherwise silently force per-iteration probes + full host
        # snapshots on every manager-less health run).
        saving = manager is not None and (
            should_save(done, checkpoint_every, num_iterations) or evicting
        )
        probing = health is not None and (
            done % health.every == 0 or done == num_iterations or saving
        )
        word = 0
        if probing:
            # Save points force a probe so a bad state is never committed.
            with metrics.phase("health_check"), \
                    span("train/health_probe", i=done):
                word = int(np.asarray(probe(u, m)))
                if ring_pending:
                    word |= _sentinel.RING_EXCHANGE
            ring_pending = False
            metrics.incr("health_checks")
        evict_reason = (
            guard.signal_name if guard is not None and guard.triggered
            else "peer process signalled"
        )
        if word and evicting:
            # Evicted at an unhealthy iteration: there is no time to climb
            # the recovery ladder, and a bad state must never be committed
            # — return the last-good factors and leave the store's newest
            # committed (healthy) step as the resume point.
            probe_summary = _sentinel.HealthReport(done, word, {}).summary()
            record_event("fault", "evicted_unhealthy", iteration=done,
                         reason=evict_reason, probe=probe_summary)
            dump_flight("evicted_unhealthy")
            anchor, (u, m) = rollback()
            metrics.gauge("preempted", 1)
            metrics.gauge("trained_iterations", anchor)
            metrics.note(
                "preempted",
                f"{evict_reason} at iteration {done} with a tripped "
                f"health probe ({probe_summary}); "
                f"returning last-good factors from iteration {anchor}",
            )
            return u, m
        if word:
            trips += 1
            report = _sentinel.HealthReport(
                iteration=done, word=word, stats={}
            )
            reports.append(report)
            metrics.incr("health_trips")
            metrics.note(f"health_trip_{trips}", report.summary())
            # Flight-record + dump before any recovery action: the ring
            # buffer's tail is the timeline of the iterations that led
            # into this trip (the chaos scenarios assert the dump's final
            # events name the fault).
            record_event("fault", "health_trip", iteration=done,
                         trip=trips, reason=report.summary())
            dump_flight(f"health_trip_{trips}")
            if trips > policy.max_recoveries:
                msg = (
                    f"health sentinel tripped {trips} times "
                    f"(> max_recoveries={policy.max_recoveries}); last: "
                    f"{report.summary()}"
                )
                if policy.on_unrecoverable == "raise":
                    record_event("fault", "unrecoverable", detail=msg)
                    dump_flight("unrecoverable")
                    raise TrainingDivergedError(msg, reports)
                anchor, (u, m) = rollback()
                record_event("fault", "degraded", detail=msg)
                dump_flight("degraded")
                metrics.gauge("degraded", 1)
                metrics.gauge("trained_iterations", anchor)
                metrics.note(
                    "degraded",
                    f"{msg}; returning last-good factors from iteration "
                    f"{anchor}",
                )
                warnings.warn(
                    f"training degraded: {msg}; returning last-good "
                    f"factors from iteration {anchor}"
                )
                return u, m
            # Write-order barrier: the replay below re-saves the same step
            # numbers; an async write for step N still in flight racing the
            # replay's fresh step-N write could commit old bytes over new.
            drain_checkpoints(manager)
            i, (u, m) = rollback()
            metrics.incr("rollbacks")
            new_overrides = policy.escalate(overrides, trips)
            backend_moved = _plan_registry.generation() != registry_gen
            escalated = new_overrides != overrides
            if escalated or backend_moved:
                detail = (
                    f"lam={new_overrides.lam:g} fused="
                    f"{new_overrides.fused_epilogue} "
                    f"algo={new_overrides.reg_solve_algo}"
                )
                if backend_moved:
                    detail += (
                        "; " + _plan_registry.REGISTRY.availability_summary()
                    )
                record_event(
                    "fault",
                    "escalation" if escalated else "backend_outage",
                    rung=trips, detail=detail,
                )
                overrides = new_overrides
                if escalated:
                    # escalation_* accounting means "a recovery rung
                    # changed the numerics knobs" — a pure backend outage
                    # reroutes kernels at UNCHANGED overrides and must
                    # not read as a λ/GJ escalation on dashboards.
                    metrics.gauge("escalation_level", trips)
                    metrics.note(f"escalation_{trips}", detail)
                # Every rung (and every backend-availability change) is a
                # PLAN TRANSITION: recorded in the provenance object the
                # checkpoint manifests and bench rows carry, so "why did
                # iteration N run on different kernels/knobs" is always
                # answerable from the artifacts.
                metrics.note(f"plan_transition_{trips}", detail)
                if plan_provenance is not None:
                    plan_provenance.record_transition(
                        "recovery_escalation" if escalated
                        else "backend_outage",
                        detail,
                    )
                if make_step is not None:
                    step = make_step(overrides)
                    registry_gen = _plan_registry.generation()
                    if watchdog is not None:
                        # The rebuilt step re-traces on its next call —
                        # minutes of tickless compile that must not read
                        # as a dead peer.
                        watchdog.extend_grace()
                else:
                    warnings.warn(
                        "escalation requested but this loop was built with "
                        "a fixed step_fn; retrying with unchanged settings"
                    )
            continue
        host_pair = None
        if saving:
            with metrics.phase("checkpoint"), \
                    span("train/checkpoint", i=done):
                # save_fn returns the host copies it gathered so the
                # rollback anchor below reuses them instead of paying
                # a second device→host gather per save point.
                host_pair = save_fn(done, u, m)
            metrics.incr("checkpoints")
        if health is not None and (saving or (manager is None and probing)):
            # Rollback anchor: mirrors every validated commit; with no
            # checkpoint store it follows the health cadence instead (the
            # snapshot is only ever taken at a probed-healthy iteration).
            good = (
                done,
                host_pair if host_pair is not None else snapshot_fn(u, m),
            )
            if manager is not None and hasattr(manager, "pin"):
                # The last verified-good step is what the recovery ladder
                # rolls back to; keep_last_n retention must never collect
                # it, however long a recovery excursion takes.
                manager.pin(done)
        if evicting:
            # Emergency save committed above (the final checkpoint rode
            # the forced save point); drain the writer so it is on disk
            # before this process dies, then exit resumable.
            drain_checkpoints(manager)
            record_event("signal", "preempted", iteration=done,
                         reason=evict_reason, committed=bool(saving))
            dump_flight("preemption")
            metrics.gauge("preempted", 1)
            metrics.gauge("trained_iterations", done)
            metrics.note(
                "preempted",
                f"{evict_reason} at iteration {done}/"
                f"{num_iterations}; final checkpoint "
                f"{'committed' if saving else 'skipped (no manager)'} — "
                "resume from the same checkpoint directory to continue",
            )
            warnings.warn(
                f"training preempted ({evict_reason}) at iteration "
                f"{done}/{num_iterations}; exiting resumable"
            )
            return u, m
        i = done
    return u, m


def _resume_or_init(state, restore_fn, init_fn):
    """Rollback target when no save point has been reached yet: the
    resumed checkpoint if the run started from one, else a deterministic
    re-init (jax PRNG keys make init replay exact)."""
    if state is not None:
        return restore_fn(state.user_factors, state.movie_factors)
    return init_fn()
