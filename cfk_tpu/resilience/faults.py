"""Deterministic fault injection: prove recovery works, don't assume it.

Every fault here is seeded and replayable — the chaos suite
(``tests/test_resilience.py``, ``scripts/chaos_lab.py``) asserts that each
injected fault class is *detected* by the sentinel, *recovered* by the
rollback/escalation policy, and that the recovered run converges to the
fault-free run's final RMSE within tolerance.  Four fault classes, each
hitting a different layer:

- ``FactorCorruption`` — NaN/Inf written into seeded rows of a factor
  buffer just before iteration ``k`` (models an HBM bit-flip / bad DMA).
- ``SingularChunk`` — zero out the factor rows feeding one solve chunk's
  normal equations; with λ=0 the chunk's Gram is exactly singular and the
  Cholesky emits NaN (models degenerate data; the policy's λ bump is the
  designed fix).
- ``TornCheckpointManager`` — a checkpoint store whose write for one
  target iteration is torn mid-"rename" (payload truncated after commit),
  exercising the crc32 manifest verification and previous-step fallback.
- ``FlakyBrokerProxy`` — a TCP proxy in front of a real broker that drops
  whole connections and delays frames per a seeded plan, exercising the
  client's connect retry/backoff and read-timeout handling.
"""

from __future__ import annotations

import dataclasses
import os
import socket
import threading
import time

import numpy as np

from cfk_tpu.transport.checkpoint import (
    CheckpointManager as _BaseCheckpointManager,
)


# --- factor-buffer faults --------------------------------------------------


@dataclasses.dataclass
class FactorCorruption:
    """Write ``value`` into ``num_rows`` seeded rows of one side's factors
    before iteration ``iteration`` (0-based).  ``persistent`` re-fires on
    every pass through that iteration (a rollback replays into the same
    fault — the escalation path must fix the math); one-shot faults model
    transients that a plain rollback+retry clears.  ``rows=(lo, hi)``
    corrupts that contiguous slice instead of seeded random rows — the
    multi-process lockstep drill uses it to land the corruption entirely
    inside ONE process's shard (entity rows are contiguously
    block-sharded), proving detection is global while the fault is local."""

    iteration: int
    side: str = "u"  # "u" | "m"
    value: float = float("nan")
    num_rows: int = 4
    seed: int = 0
    persistent: bool = False
    rows: tuple[int, int] | None = None
    fired: int = 0

    def apply(self, i: int, u, m):
        if i != self.iteration or (self.fired and not self.persistent):
            return u, m
        self.fired += 1
        import jax.numpy as jnp

        target = u if self.side == "u" else m
        if self.rows is not None:
            lo, hi = self.rows
            target = target.at[lo:hi].set(self.value)
        else:
            rows = np.random.default_rng(self.seed).choice(
                target.shape[0], size=min(self.num_rows, target.shape[0]),
                replace=False,
            )
            target = target.at[jnp.asarray(rows)].set(self.value)
        return (target, m) if self.side == "u" else (u, target)


@dataclasses.dataclass
class SingularChunk:
    """Zero a contiguous slice of the fixed side's factor rows before
    iteration ``iteration`` so the entities whose neighbor lists live
    entirely in that slice assemble an exactly-singular A = Σ f·fᵀ (run
    with λ=0 to remove the SPD repair term — the escalation ladder's λ
    bump is then precisely the recovery).  ``rows=None`` zeroes the whole
    side — every chunk's normal equations go singular at once."""

    iteration: int
    side: str = "u"
    rows: tuple[int, int] | None = None
    persistent: bool = True
    fired: int = 0

    def apply(self, i: int, u, m):
        if i != self.iteration or (self.fired and not self.persistent):
            return u, m
        self.fired += 1
        target = u if self.side == "u" else m
        lo, hi = self.rows if self.rows is not None else (0, target.shape[0])
        target = target.at[lo:hi].set(0.0)
        return (target, m) if self.side == "u" else (u, target)


@dataclasses.dataclass
class BackendOutage:
    """Force a kernel backend unavailable mid-run (ISSUE 9 plan_fallback).

    At iteration ``iteration``: (a) mark ``backend`` unavailable in the
    kernel registry — every mode resolver consults availability at trace
    time, so the next step REBUILD resolves to the ``xla_emulation``
    degradation floor — and (b) corrupt a few factor rows to NaN, the
    observable symptom of a backend failing under the feet of an
    already-compiled program.  The sentinel trips, the resilient loop
    rolls back, sees the registry generation moved, rebuilds the step
    (a plan transition at unchanged escalation overrides), and the replay
    runs on the emulation backend — bit-exact factors, because the
    gather/fused knob routes are bit-identical by contract.

    The caller restores availability (``restore()`` or a try/finally);
    the fault only breaks things.
    """

    iteration: int
    backend: str = "mosaic_tpu"
    num_rows: int = 4
    seed: int = 0
    fired: int = 0

    def apply(self, i: int, u, m):
        if i != self.iteration or self.fired:
            return u, m
        self.fired += 1
        from cfk_tpu.plan.registry import REGISTRY

        REGISTRY.force_unavailable(self.backend, True)
        import jax.numpy as jnp

        rows = np.random.default_rng(self.seed).choice(
            u.shape[0], size=min(self.num_rows, u.shape[0]), replace=False,
        )
        return u.at[jnp.asarray(rows)].set(float("nan")), m

    def restore(self) -> None:
        from cfk_tpu.plan.registry import REGISTRY

        REGISTRY.force_unavailable(self.backend, False)


class FaultInjector:
    """The hook the resilient loop calls: a seeded plan of factor faults.

    ``before_step(i, u, m)`` applies every armed fault due at iteration
    ``i`` and returns the (possibly corrupted) pair.  Passing an injector
    to a trainer forces the stepped (resilient) loop — faults fire at step
    boundaries, which the fused ``fori_loop`` does not expose.
    """

    def __init__(self, *faults):
        self.faults = list(faults)

    def before_step(self, i: int, u, m):
        for f in self.faults:
            u, m = f.apply(i, u, m)
        return u, m

    @property
    def fired(self) -> int:
        return sum(f.fired for f in self.faults)


# --- host-window faults (cfk_tpu.offload, ISSUE 11) ------------------------


@dataclasses.dataclass
class HostWindowCorruption:
    """Corrupt ONE staged host window in flight (PCIe bit-rot / a torn
    host read) before it reaches the device.  Fires when the windowed
    driver stages ``(iteration, side, window)``; the host store itself
    stays intact, so a rollback + replay (the fault is one-shot) recovers
    to bit-exact factors — the transient-fault contract of the ladder's
    rung 1.

    ``kind="nan"`` poisons ``num_rows`` seeded rows; ``kind="torn"``
    replaces the window's second half with stale zeros (a partially
    completed staging read — values are WRONG but finite, caught by the
    row-norm watchdog or the divergence it causes rather than isfinite).
    ``shard`` (sharded windowed driver, ISSUE 12) restricts the fault to
    ONE shard's staging pipeline — None matches any shard (the
    single-shard driver stages as shard 0).
    """

    iteration: int
    side: str = "m"  # which half-step's staging ("m" | "u")
    window: int = 0
    kind: str = "nan"  # "nan" | "torn"
    num_rows: int = 4
    seed: int = 0
    persistent: bool = False
    shard: int | None = None
    fired: int = 0
    # Thread names the fault fired on (ISSUE 13): the pooled staging
    # engine runs window staging on worker threads, and the chaos
    # staging_pool scenario asserts the injection really happened INSIDE
    # a pool worker ("cfk-stage-*"), not on the consuming thread.
    fired_in: list = dataclasses.field(default_factory=list)

    def apply_window(self, i: int, side: str, w: int,
                     tbl: np.ndarray, shard: int = 0) -> np.ndarray:
        if (i != self.iteration or side != self.side or w != self.window
                or (self.shard is not None and shard != self.shard)
                or (self.fired and not self.persistent)):
            return tbl
        self.fired += 1
        self.fired_in.append(threading.current_thread().name)
        tbl = np.array(tbl)  # never mutate the store's rows
        if self.kind == "torn":
            tbl[tbl.shape[0] // 2:] = 0.0
            return tbl
        rows = np.random.default_rng(self.seed).choice(
            tbl.shape[0], size=min(self.num_rows, tbl.shape[0]),
            replace=False,
        )
        tbl[rows] = np.float32(np.nan)
        return tbl


@dataclasses.dataclass
class HotCacheCorruption:
    """Poison the DEVICE-RESIDENT hot partition (ISSUE 15) — an HBM
    bit-flip / DMA fault landing in the skew-aware hot-row cache rather
    than a staged window.  Fires when the windowed driver is about to
    READ the ``(iteration, side)`` half's fixed partition; the driver
    NaNs ``num_rows`` partition positions (the int8 pair poisons the
    per-row scale — the one leaf that can go nonfinite).  The host
    master store is untouched, so the sentinel trip that follows rolls
    back and the partition REBUILD from the master recovers bit-exact
    factors — the hot-cache analog of ``HostWindowCorruption``'s
    transient-fault contract."""

    iteration: int
    side: str = "m"
    num_rows: int = 4
    seed: int = 0
    persistent: bool = False
    fired: int = 0

    def apply_hot(self, i: int, side: str,
                  partition_rows: int = 0) -> np.ndarray | None:
        if (i != self.iteration or side != self.side
                or partition_rows < 1
                or (self.fired and not self.persistent)):
            return None
        self.fired += 1
        return np.random.default_rng(self.seed).choice(
            partition_rows, size=min(self.num_rows, partition_rows),
            replace=False,
        )


@dataclasses.dataclass
class SlowHostFetch:
    """Delay plan for window staging (a contended host / remote-NUMA
    fetch):
    sleep ``delay_s`` before every ``every``-th staging.  Purely a timing
    fault — the staging engine (pooled or serial double buffer) must
    absorb it without touching the math (the chaos scenario pins
    bit-exact factors under delay).  ``fired`` counts DELAYS actually
    injected (not staging calls — the chaos row's fault accounting must
    not inflate), under a lock: the pooled engine stages concurrently
    from worker threads, and an unguarded ``calls`` cadence would race.
    ``only_shard`` restricts the slowdown to one shard's staging (the
    straggler-host drill — the pool keeps the OTHER shards staging while
    this one sleeps, which the scenario proves via pool_peak_inflight)."""

    delay_s: float = 0.01
    every: int = 1
    only_shard: int | None = None
    fired: int = 0
    calls: int = 0
    _lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False,
    )

    def delay(self, i: int, side: str, w: int, shard: int = 0) -> None:
        if self.every < 1:
            return
        if self.only_shard is not None and shard != self.only_shard:
            return
        with self._lock:
            self.calls += 1
            due = self.calls % self.every == 0
            if due:
                self.fired += 1
        if due:
            time.sleep(self.delay_s)


@dataclasses.dataclass
class StagingCrash:
    """Raise an arbitrary exception from INSIDE one window's staging
    (ISSUE 13) — a host allocator failure, a dead NUMA node, any
    non-checksum staging error.  The pooled engine's contract under
    test: a worker exception must propagate to the caller as the staging
    error (``WindowStager.take`` re-raises and cancels the remaining
    tasks) — never a hang, and never a half-staged window reaching a
    kernel.  Fires via the ``WindowFaultInjector.apply_window`` hook, so
    it lands exactly where real staging work runs (a pool worker thread
    in pooled mode)."""

    iteration: int
    side: str = "m"
    window: int = 0
    shard: int | None = None
    message: str = "injected staging crash"
    fired: int = 0
    fired_in: list = dataclasses.field(default_factory=list)

    def apply_window(self, i: int, side: str, w: int,
                     tbl: np.ndarray, shard: int = 0) -> np.ndarray:
        if (i != self.iteration or side != self.side or w != self.window
                or (self.shard is not None and shard != self.shard)
                or self.fired):
            return tbl
        self.fired += 1
        self.fired_in.append(threading.current_thread().name)
        raise RuntimeError(self.message)


@dataclasses.dataclass
class StoreBitRot:
    """Flip one byte of a ``HostFactorStore`` shard after iteration
    ``iteration`` commits — silent host-RAM bit-rot landing in the
    MASTER factors (not a staged window: the store itself is now wrong,
    so a plain rollback replay would re-read the rotten rows).  The
    per-shard integrity seals (``HostFactorStore.seal``/``scrub``,
    ISSUE 20) must detect it loudly (``StoreIntegrityError``) and the
    driver must repair from the last committed checkpoint."""

    iteration: int
    side: str = "u"  # which store ("u" | "m")
    shard: int = 0
    byte: int = 0
    fired: int = 0

    def apply_store(self, i: int, side: str, store) -> None:
        if i != self.iteration or side != self.side or self.fired:
            return
        self.fired += 1
        buf = store._shards[self.shard].view(np.uint8).reshape(-1)
        buf[self.byte % buf.size] ^= 0xFF


class FlakyFleet:
    """A fleet proxy whose first ``fail`` collective calls raise
    ``error`` (default ``TransientFleetError``) — the slow-GC-pause /
    dropped-packet fault the transient-vs-fatal classifier must absorb
    with bounded retries instead of declaring the peer dead.  Set
    ``fail`` high (or ``error`` to a fatal type) to test the
    declare-dead path.  ``failed``/``calls`` count firings."""

    def __init__(self, base, *, fail: int = 1, error=None):
        from cfk_tpu.offload.elastic import TransientFleetError

        self.base = base
        self.fail = int(fail)
        self.error = error or TransientFleetError("injected fleet flake")
        self.failed = 0
        self.calls = 0

    @property
    def num_processes(self) -> int:
        return self.base.num_processes

    @property
    def process(self) -> int:
        return self.base.process

    def _flake(self) -> None:
        self.calls += 1
        if self.failed < self.fail:
            self.failed += 1
            raise self.error

    def allgather_bytes(self, payload):
        self._flake()
        return self.base.allgather_bytes(payload)

    def allgather_i32(self, values):
        self._flake()
        return self.base.allgather_i32(values)


class WindowFaultInjector:
    """The hook ``offload.windowed`` calls while staging: applies every
    armed window corruption and delay plan.  The window-level analog of
    ``FaultInjector`` (which operates on factor buffers at step
    boundaries)."""

    def __init__(self, *faults):
        self.faults = list(faults)

    def apply_window(self, i: int, side: str, w: int,
                     tbl: np.ndarray, shard: int = 0) -> np.ndarray:
        for f in self.faults:
            if hasattr(f, "apply_window"):
                tbl = f.apply_window(i, side, w, tbl, shard=shard)
        return tbl

    def delay(self, i: int, side: str, w: int, shard: int = 0) -> None:
        for f in self.faults:
            if hasattr(f, "delay"):
                f.delay(i, side, w, shard=shard)

    def apply_hot(self, i: int, side: str,
                  partition_rows: int = 0) -> np.ndarray | None:
        """Poison positions for the (iteration, side) half's hot
        partition, or None (ISSUE 15 — ``HotCacheCorruption``)."""
        for f in self.faults:
            if hasattr(f, "apply_hot"):
                rows = f.apply_hot(i, side, partition_rows)
                if rows is not None:
                    return rows
        return None

    def apply_store(self, i: int, side: str, store) -> None:
        """Fire master-store faults (``StoreBitRot``) for the just-
        committed iteration ``i``'s ``side`` table (ISSUE 20)."""
        for f in self.faults:
            if hasattr(f, "apply_store"):
                f.apply_store(i, side, store)

    @property
    def fired(self) -> int:
        return sum(f.fired for f in self.faults)


# --- checkpoint faults -----------------------------------------------------


class TornCheckpointManager:
    """Wrap a ``CheckpointManager`` so the save at ``tear_at`` is torn.

    ``mode="truncate"`` halves one npy payload after the step directory is
    committed (a torn write that raced the rename); ``mode="scramble"``
    flips bytes in place (silent media corruption); ``mode="manifest"``
    truncates ``manifest.json`` itself.  All three must be caught by the
    crc32 manifest verification on restore, which then falls back to the
    previous complete step.
    """

    def __init__(self, inner, tear_at: int, mode: str = "truncate",
                 victim: str = "user.npy"):
        if mode not in ("truncate", "scramble", "manifest"):
            raise ValueError(f"unknown tear mode {mode!r}")
        self.inner = inner
        self.tear_at = tear_at
        self.mode = mode
        self.victim = victim
        self.torn: list[str] = []

    def __getattr__(self, name):  # delegate everything else
        return getattr(self.inner, name)

    def save_async(self, iteration, user_factors, movie_factors, meta=None):
        # Pin the SYNC path: delegating to the inner writer thread would
        # route around this wrapper's tear (the thread calls inner.save),
        # and the fault must land deterministically before training moves
        # on.  The loop's drain barriers are no-ops against this store.
        self.save(iteration, user_factors, movie_factors, meta=meta)

    def save(self, iteration, user_factors, movie_factors, meta=None):
        path = self.inner.save(iteration, user_factors, movie_factors,
                               meta=meta)
        if iteration == self.tear_at:
            victim = os.path.join(
                path, "manifest.json" if self.mode == "manifest"
                else self.victim,
            )
            data = open(victim, "rb").read()
            if self.mode == "scramble":
                torn = bytes(b ^ 0xFF for b in data[: len(data) // 2])
                torn += data[len(data) // 2:]
            else:
                torn = data[: max(1, len(data) // 2)]
            with open(victim, "wb") as f:
                f.write(torn)
            self.torn.append(victim)
        return path


class SlowDiskCheckpointManager(_BaseCheckpointManager):
    """Checkpoint store on a pathologically slow disk: every step write
    sleeps ``delay_s`` before touching the filesystem.

    A *subclass* of ``CheckpointManager`` (not a delegating wrapper) so the
    inherited ``save_async`` hands THIS slow ``save`` to the background
    writer thread — the chaos scenario that proves the step loop never
    stalls behind the writer, and that back-pressure (``max_pending``)
    throttles the producer instead of growing an unbounded snapshot queue.
    ``writes``/``max_pending_seen`` record that the fault actually fired.
    """

    def __init__(self, directory, *, delay_s=0.05, **kw):
        super().__init__(directory, **kw)
        self.delay_s = delay_s
        self.writes = 0
        self.max_pending_seen = 0

    def save(self, iteration, user_factors, movie_factors, meta=None):
        self.max_pending_seen = max(self.max_pending_seen,
                                    self.pending_count)
        time.sleep(self.delay_s)
        self.writes += 1
        return super().save(iteration, user_factors, movie_factors,
                            meta=meta)


@dataclasses.dataclass
class PreemptAt:
    """Deliver ``signum`` (default SIGTERM) to this very process before
    iteration ``iteration`` — the eviction notice a preempted VM gets.  A
    ``PreemptionGuard`` must be armed: its handler turns the signal into
    the graceful save-and-exit the loop polls for.  ``only_process``
    restricts delivery under multi-process JAX (e.g. kill exactly one
    worker with ``signal.SIGKILL`` for the dead-collective drill)."""

    iteration: int
    signum: int = 15  # signal.SIGTERM
    only_process: int | None = None
    fired: int = 0

    def apply(self, i: int, u, m):
        if i != self.iteration or self.fired:
            return u, m
        if self.only_process is not None:
            import jax

            if jax.process_index() != self.only_process:
                return u, m
        self.fired += 1
        os.kill(os.getpid(), self.signum)
        return u, m


# --- broker transport faults ----------------------------------------------


@dataclasses.dataclass(frozen=True)
class FlakyPlan:
    """Deterministic misbehavior schedule for the broker fault proxies.

    Byte-level faults (``FlakyBrokerProxy``, a TCP proxy):

    ``drop_first_connects`` — accept then immediately close that many
    connections (a broker still binding its listener / a dying LB
    backend); the client's connect/request retry must back off and win.
    ``delay_frames`` — hold each forwarded chunk of the first surviving
    connection for ``frame_delay`` seconds (congestion); the client's
    read timeout must be patient enough or retry.

    Record-level delivery faults (``FlakyTransport``, a ``Transport``
    proxy — the at-least-once semantics a Kafka consumer actually faces,
    which raw TCP byte faults cannot express without corrupting framing):

    ``duplicate`` — re-deliver every ``duplicate``-th consumed record a
    second time (at-least-once redelivery); the streaming consumer must
    drop the copy by offset.
    ``reorder`` — shuffle delivery order within seeded windows of this
    many records (interleaved fetches / a racy poll); the consumer must
    heal order by offset sort.
    ``drop`` — omit every ``drop``-th record from a delivery pass, at
    most ``drop_passes`` times per record (a lost fetch; the transport
    still HAS the record — re-polling must recover it).
    ``seed`` — the reorder shuffle's PRNG seed.
    """

    drop_first_connects: int = 0
    delay_frames: int = 0
    frame_delay: float = 0.05
    duplicate: int = 0
    reorder: int = 0
    drop: int = 0
    drop_passes: int = 1
    seed: int = 0


class FlakyBrokerProxy:
    """A localhost TCP proxy in front of a real broker, misbehaving to plan.

    Forwards bytes both ways once a connection survives the plan; every
    drop/delay is counted so tests assert the fault actually happened
    (a chaos test that passes without injecting anything proves nothing).

    This proxy owns the BYTE-level faults of a ``FlakyPlan`` (connection
    drops, frame delays).  The plan's RECORD-level delivery faults —
    ``duplicate``/``reorder``/``drop`` — are applied by ``FlakyTransport``
    instead: duplicating raw TCP bytes would corrupt the length-prefixed
    framing into garbage, whereas real at-least-once brokers duplicate and
    reorder *records* with intact payloads, which is the failure mode the
    streaming consumer's exactly-once assembly must survive.
    """

    def __init__(self, upstream_port: int, plan: FlakyPlan):
        self.upstream_port = upstream_port
        self.plan = plan
        self.dropped = 0
        self.delayed = 0
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(8)
        self.port = self._lsock.getsockname()[1]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._accepted = 0
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            self._accepted += 1
            if self._accepted <= self.plan.drop_first_connects:
                self.dropped += 1
                conn.close()
                continue
            up = socket.create_connection(("127.0.0.1", self.upstream_port))
            for src, dst, slow in ((conn, up, False), (up, conn, True)):
                t = threading.Thread(
                    target=self._pump, args=(src, dst, slow), daemon=True
                )
                t.start()
                self._threads.append(t)

    def _pump(self, src, dst, slow):
        frames = 0
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                if slow and frames < self.plan.delay_frames:
                    frames += 1
                    self.delayed += 1
                    time.sleep(self.plan.frame_delay)
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    def close(self):
        self._stop.set()
        self._lsock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FlakyTransport:
    """A ``Transport`` proxy that misdelivers records per a ``FlakyPlan``.

    Produce/admin calls pass through untouched — the faults live purely in
    ``consume``, i.e. between the durable log and the reader, which is
    exactly where Kafka's at-least-once semantics misbehave: records may
    arrive duplicated (``plan.duplicate``), out of order within a window
    (``plan.reorder``), or missing from a pass (``plan.drop``, recovered
    on a later poll — the transport never loses the record, only a
    delivery of it).  Each fault is counted (``duplicated``/``reordered``/
    ``dropped``) so chaos tests can assert the fault actually fired.
    Deterministic: the reorder shuffle is seeded per (partition, pass) and
    the duplicate/drop cadences are positional.
    """

    def __init__(self, inner, plan: FlakyPlan):
        self.inner = inner
        self.plan = plan
        self.duplicated = 0
        self.reordered = 0
        self.dropped = 0
        self._passes = 0
        self._drop_seen: dict[tuple[str, int, int], int] = {}

    def __getattr__(self, name):  # produce/create_topic/end_offset/... pass through
        return getattr(self.inner, name)

    def consume(self, topic, partition, start_offset=0):
        records = list(self.inner.consume(topic, partition, start_offset))
        self._passes += 1
        plan = self.plan
        out = []
        for i, rec in enumerate(records):
            if plan.drop:
                key = (topic, partition, rec.offset)
                if (i + 1) % plan.drop == 0 and \
                        self._drop_seen.get(key, 0) < plan.drop_passes:
                    self._drop_seen[key] = self._drop_seen.get(key, 0) + 1
                    self.dropped += 1
                    continue
            out.append(rec)
            if plan.duplicate and (i + 1) % plan.duplicate == 0:
                out.append(rec)
                self.duplicated += 1
        if plan.reorder and len(out) > 1:
            rng = np.random.default_rng(
                (plan.seed, partition, self._passes)
            )
            w = max(2, plan.reorder)
            for lo in range(0, len(out), w):
                window = out[lo:lo + w]
                perm = rng.permutation(len(window))
                if not np.array_equal(perm, np.arange(len(window))):
                    self.reordered += len(window)
                out[lo:lo + w] = [window[j] for j in perm]
        yield from out


class DeltaStreamTamper:
    """A ``Transport`` proxy that PERMANENTLY hides chosen frames of one
    topic from consumers — the factor-delta gap fault (ISSUE 18).

    ``FlakyTransport.drop`` models a missed *delivery*: the record comes
    back on a later pass, which seq-ordered apply absorbs silently.  This
    wrapper models the loss the delta protocol must detect LOUDLY — a
    frame that never arrives (compacted away, crossed a retention
    boundary, or corrupted at rest): offsets in ``hide`` (per ``topic``)
    vanish from every consume pass, so the replica's next frame skips a
    seq and the gap→snapshot-resync path has to fire.  ``mode="truncate"``
    instead delivers the frame with its payload cut in half — the
    undecodable-frame spelling of the same gap.  ``hidden``/``truncated``
    count firings so the chaos test can assert the fault actually
    happened."""

    def __init__(self, inner, *, topic: str, hide=(), mode: str = "hide"):
        if mode not in ("hide", "truncate"):
            raise ValueError(f"mode must be hide|truncate, got {mode!r}")
        self.inner = inner
        self.topic = topic
        self.hide = set(int(o) for o in hide)
        self.mode = mode
        self.hidden = 0
        self.truncated = 0

    def __getattr__(self, name):  # produce/create_topic/... pass through
        return getattr(self.inner, name)

    def consume(self, topic, partition, start_offset=0):
        for rec in self.inner.consume(topic, partition, start_offset):
            if topic == self.topic and rec.offset in self.hide:
                if self.mode == "hide":
                    self.hidden += 1
                    continue
                import dataclasses

                self.truncated += 1
                rec = dataclasses.replace(
                    rec, value=rec.value[: max(1, len(rec.value) // 2)]
                )
            yield rec


def blockstructured_coo(
    num_users: int = 24,
    num_movies: int = 16,
    isolated_movies: int = 4,
    isolated_users: int = 8,
    seed: int = 0,
):
    """Small dense-ish COO where the first ``isolated_movies`` movies are
    rated ONLY by the first ``isolated_users`` users (who also rate the
    shared movies).  Zeroing those users' factor rows (``SingularChunk``)
    then makes exactly the isolated movies' normal equations singular
    under λ=0, while the rest of the problem stays healthy — the shaped
    fixture the singular-chunk chaos tests train on.  Every entity has
    plenty of neighbors, so the λ=0 *fault-free* run is generically
    non-singular (unlike power-law synthetic data, where low-degree
    entities are singular at λ=0 on their own).
    """
    from cfk_tpu.data.blocks import RatingsCOO

    rng = np.random.default_rng(seed)
    movies, users = [], []
    for mv in range(num_movies):
        raters = (
            range(isolated_users) if mv < isolated_movies
            else range(num_users)
        )
        for us in raters:
            movies.append(mv)
            users.append(us)
    movies = np.asarray(movies, np.int64)
    users = np.asarray(users, np.int64)
    ratings = rng.integers(1, 6, size=movies.shape[0]).astype(np.float32)
    return RatingsCOO(movie_raw=movies, user_raw=users, rating=ratings)


def crc32_file(path: str) -> int:
    """crc32 of a file's bytes — THE checkpoint manifest payload checksum
    (one implementation; a drifted copy here would make the chaos tests
    verify against a stale scheme)."""
    from cfk_tpu.transport.checkpoint import _crc32_file

    return _crc32_file(path)
