"""Recovery policy: the rollback/escalation ladder for tripped probes.

On a sentinel trip the resilient loop rolls back to the last good state
(last committed checkpoint, or the in-memory snapshot mirror when no
checkpoint store is configured) and climbs one rung of the escalation
ladder before retrying:

  1. **retry** — rollback only, no config change.  Transient corruption
     (a one-shot bit flip, an injected NaN) replays cleanly because the
     iteration math is deterministic.
  2. **bump λ** — multiply the regularizer by ``lam_factor`` (from
     ``lam_floor`` when λ was 0).  Fixes genuinely singular or
     near-singular normal equations — ALS-WR's λ·n·I is exactly the SPD
     repair knob.
  3. **split epilogue** — pin ``fused_epilogue=False``: the fused
     in-VMEM Gram+solve kernel steps aside for the split Gram→HBM→solve
     schedule (the simpler, longest-soaked code path), and λ stays
     bumped.
  4. **GJ elimination** — swap the fused reg+solve kernels' reverse-LU
     for Gauss-Jordan.  ``reg_solve_algo`` is a REAL threaded parameter
     now (``ALSConfig.reg_solve_algo`` → the half-step dispatchers'
     ``algo=`` kwargs, a jit-static), so the rebuilt step re-traces with
     the override by construction — it no longer rides the
     ``CFK_REG_SOLVE_ALGO`` env var, whose trace-time read made the rung
     depend on a paired λ bump to force the re-trace (and leaked process
     state the loop had to save/restore).  λ is still bumped here: GJ is
     reached when the systems are badly conditioned, and the extra ridge
     is the actual SPD repair.

Rungs are cumulative, and settings stay escalated for the rest of the run
(a run that needed λ·10 to stay SPD will need it again).  After
``max_recoveries`` total trips the loop stops retrying and degrades
gracefully: return the last-good factors with a diagnostic report instead
of crashing (``on_unrecoverable="raise"`` opts into the crash).
"""

from __future__ import annotations

import dataclasses


class TrainingDivergedError(RuntimeError):
    """Raised when recovery is exhausted and ``on_unrecoverable="raise"``."""

    def __init__(self, message: str, reports=()):  # reports: [HealthReport]
        super().__init__(message)
        self.reports = list(reports)


@dataclasses.dataclass(frozen=True)
class Overrides:
    """The step-build knobs one escalation rung may change.

    All three are threaded step-build parameters: ``make_step(Overrides)``
    rebuilds the jitted step with them as jit-statics, so every rung
    re-traces with its override picked up (``reg_solve_algo`` included —
    the env-var indirection is gone).
    """

    lam: float
    fused_epilogue: bool | None = None
    reg_solve_algo: str | None = None  # None = leave the config/process default


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """Bounds and factors of the escalation ladder (see module docstring)."""

    max_recoveries: int = 4
    lam_factor: float = 10.0
    lam_floor: float = 1e-4  # the bump target when λ was exactly 0
    on_unrecoverable: str = "degrade"  # or "raise"

    def __post_init__(self) -> None:
        if self.max_recoveries < 0:
            raise ValueError(
                f"max_recoveries must be >= 0, got {self.max_recoveries}"
            )
        if self.lam_factor <= 1.0:
            raise ValueError(
                f"lam_factor must be > 1 (it escalates λ), got "
                f"{self.lam_factor}"
            )
        if self.on_unrecoverable not in ("degrade", "raise"):
            raise ValueError(
                "on_unrecoverable must be 'degrade' or 'raise', got "
                f"{self.on_unrecoverable!r}"
            )

    def _bump(self, lam: float) -> float:
        return lam * self.lam_factor if lam > 0 else self.lam_floor

    def escalate(self, current: Overrides, level: int) -> Overrides:
        """Overrides for escalation rung ``level`` (1-based trip count).

        Level 1 keeps ``current`` (plain rollback+retry); each later level
        applies its rung cumulatively on top of the previous overrides.
        Levels past the ladder keep escalating λ — by then the run is
        either recovering or burning through its bounded retries.
        """
        if level <= 1:
            return current
        if level == 2:
            return dataclasses.replace(current, lam=self._bump(current.lam))
        if level == 3 and current.fused_epilogue is not False:
            return dataclasses.replace(current, fused_epilogue=False)
        # Rung 4 — also taken at level 3 when the split epilogue is
        # already pinned (a no-op rung would burn one of the bounded
        # retries on an identical, guaranteed-to-re-trip replay).
        return dataclasses.replace(
            current, lam=self._bump(current.lam), reg_solve_algo="gj"
        )


def policy_from_config(config) -> RecoveryPolicy:
    """The recovery policy an ``ALSConfig`` selects."""
    return RecoveryPolicy(
        max_recoveries=config.max_recoveries,
        lam_factor=config.lam_escalation,
        on_unrecoverable=config.on_unrecoverable,
    )
