"""Self-healing training: health sentinel, recovery policy, fault injection.

The reference prototype has zero fault tolerance — any crash triggers
``streams.cleanUp()`` and a from-scratch restart (SURVEY.md §5), and dense
float32 ALS can silently diverge with no in-loop detection.  This package
adds the three coupled pieces production matrix factorization needs
(ALX, PAPERS.md, treats them as table stakes):

- ``sentinel`` — cheap on-device numerical-health probes (``isfinite``
  reductions + factor-norm watchdogs) folded into the iteration carry or
  evaluated on a cadence from the stepped training loops.
- ``policy`` — the rollback/escalation recovery ladder: on a tripped probe,
  roll back to the last good checkpoint and retry, then bump λ, then pin
  the split Gram→solve epilogue, then swap the LU elimination for
  Gauss-Jordan; bounded retries before gracefully degrading to
  "last-good factors + diagnostic report".
- ``loop`` — the resilient stepped training loop every trainer shares
  (single-device and SPMD), wiring sentinel + policy + checkpoint
  rollback together.
- ``faults`` — seeded, deterministic fault injection (NaN/Inf factor
  corruption, singular normal equations, torn checkpoint writes, flaky
  broker connections) so recovery is *proved*, not assumed
  (``tests/test_resilience.py``, ``scripts/chaos_lab.py``).
- ``preempt`` — infrastructure-fault tolerance: ``PreemptionGuard``
  (SIGTERM/SIGINT → drain the async checkpoint writer, commit one final
  checkpoint, exit resumable) and ``StallWatchdog`` (bounded exit with an
  intact checkpoint store when a dead peer wedges a collective).
- ``retry`` — exponential backoff + jitter helpers shared with the TCP
  transport.
"""

from cfk_tpu.resilience.preempt import (
    STALL_EXIT_CODE,
    PreemptionGuard,
    StallWatchdog,
)
from cfk_tpu.resilience.policy import (
    Overrides,
    RecoveryPolicy,
    TrainingDivergedError,
)
from cfk_tpu.resilience.sentinel import (
    HealthConfig,
    HealthReport,
    describe_word,
    health_from_config,
)

__all__ = [
    "HealthConfig",
    "HealthReport",
    "Overrides",
    "PreemptionGuard",
    "RecoveryPolicy",
    "STALL_EXIT_CODE",
    "StallWatchdog",
    "TrainingDivergedError",
    "describe_word",
    "health_from_config",
]
