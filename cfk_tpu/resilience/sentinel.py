"""Numerical-health sentinel: cheap on-device probes over the factor state.

A diverged ALS run is cheap to detect and expensive to miss: one NaN in a
factor row poisons every Gram that row touches on the next half-iteration,
so by the time the final RMSE is computed the whole model is garbage.  The
probes here are O(E·k) reductions — two ``isfinite`` all-reduces and two
max-row-norm watchdogs over U/M — against the iteration's O(nnz·k + E·k²)
solve work, so they are effectively free (< 2% s/iter measured at the
bench dense-stream config with ``health_check_every=1``; ``scripts/
perf_lab.py --health`` records the axis).

Two consumption modes, one probe:

- **in-carry** (fused ``fori_loop`` paths, ``fold_probe``): the probe word
  rides the loop carry as an int32 pair ``[first_bad_iter, reasons]``;
  the host inspects it once after the loop.
- **stepped** (checkpointed / SPMD loops, ``probe_word``): the jitted word
  is fetched on the ``health_check_every`` cadence; the reductions run on
  sharded arrays unchanged (XLA inserts the collectives).

Reason bits compose, so one word carries every tripped condition.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

# Reason bits of the probe word (compose with |).
NONFINITE_U = 1  # NaN/Inf in the user factors
NONFINITE_M = 2  # NaN/Inf in the movie factors
NORM_U = 4  # a user factor row's 2-norm exceeded the watchdog limit
NORM_M = 8  # a movie factor row's 2-norm exceeded the watchdog limit
RING_EXCHANGE = 16  # a ring-rotated factor block went non-finite in flight

_REASONS = {
    NONFINITE_U: "nonfinite_user_factors",
    NONFINITE_M: "nonfinite_movie_factors",
    NORM_U: "user_norm_watchdog",
    NORM_M: "movie_norm_watchdog",
    RING_EXCHANGE: "ring_exchange_corruption",
}


def describe_word(word: int) -> list[str]:
    """Human-readable reasons for a tripped probe word."""
    return [name for bit, name in _REASONS.items() if word & bit]


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Sentinel knobs resolved from ``ALSConfig`` (``health_from_config``)."""

    every: int = 1  # evaluate the probe every N completed iterations
    norm_limit: float = 1e6  # max factor-row 2-norm before the watchdog trips


@dataclasses.dataclass(frozen=True)
class HealthReport:
    """Host-side diagnostic for one sentinel trip (or a clean run)."""

    iteration: int  # first iteration whose probe tripped; -1 = healthy
    word: int  # reason bitmask (0 = healthy)
    stats: dict  # max row norms etc. at detection time (may be empty)

    @property
    def healthy(self) -> bool:
        return self.word == 0

    @property
    def reasons(self) -> list[str]:
        return describe_word(self.word)

    def summary(self) -> str:
        if self.healthy:
            return "healthy"
        parts = ",".join(self.reasons)
        return f"iteration {self.iteration}: {parts}"


def health_from_config(config) -> HealthConfig | None:
    """The sentinel config an ``ALSConfig`` selects, or None when off."""
    every = getattr(config, "health_check_every", None)
    if every is None:
        return None
    return HealthConfig(
        every=every, norm_limit=config.health_norm_limit
    )


def probe_word(u: jax.Array, m: jax.Array, norm_limit: float) -> jax.Array:
    """int32 reason bitmask over the factor pair; 0 = healthy.

    Pure jnp reductions — jit/shard-map/fori-loop safe, and correct on
    row-sharded global arrays (the all-reduce is XLA's problem).  The norm
    watchdog compares squared row norms so no sqrt is paid; an Inf row
    trips both its non-finite bit and its norm bit, which is fine — bits
    compose.
    """
    limit_sq = jnp.float32(float(norm_limit)) ** 2

    def side(x, nonfinite_bit, norm_bit):
        xf = x.astype(jnp.float32)
        finite = jnp.all(jnp.isfinite(xf))
        norm_sq = jnp.max(jnp.sum(jnp.square(xf), axis=-1))
        w = jnp.where(finite, jnp.int32(0), jnp.int32(nonfinite_bit))
        return w | jnp.where(
            norm_sq > limit_sq, jnp.int32(norm_bit), jnp.int32(0)
        )

    return side(u, NONFINITE_U, NORM_U) | side(m, NONFINITE_M, NORM_M)


@jax.jit
def health_stats(u: jax.Array, m: jax.Array) -> jax.Array:
    """[max_row_norm_u, max_row_norm_m] float32 — the diagnostic detail a
    tripped probe's report carries (one fetch, two scalars)."""
    row_norm = lambda x: jnp.sqrt(
        jnp.max(jnp.sum(jnp.square(x.astype(jnp.float32)), axis=-1))
    )
    return jnp.stack([row_norm(u), row_norm(m)])


def carry_init() -> jax.Array:
    """Fresh in-carry health word: ``[first_bad_iter=-1, reasons=0]``."""
    return jnp.array([-1, 0], jnp.int32)


def fold_probe(
    hw: jax.Array,
    i,
    u: jax.Array,
    m: jax.Array,
    *,
    every: int,
    norm_limit: float,
    total: int | None = None,
) -> jax.Array:
    """Fold one iteration's probe into the carried health word.

    Evaluates the probe only on the ``every`` cadence and only while the
    word is still clean (``lax.cond`` skips the reductions entirely on
    off-cadence iterations — the near-zero-overhead contract).  ``i`` is
    the zero-based iteration index; cadence counts completed iterations,
    matching the stepped loops.  Pass the loop's ``total`` iteration
    count so the FINAL iteration is always probed even when ``total`` is
    not a multiple of ``every`` — the returned state must never dodge
    the sentinel (the stepped loops force the same final probe).
    """
    due = ((i + 1) % every == 0) & (hw[0] < 0)
    if total is not None:
        due = due | ((i + 1 == total) & (hw[0] < 0))

    def check(hw):
        w = probe_word(u, m, norm_limit)
        tripped = w > 0
        return jnp.where(
            tripped,
            jnp.stack([jnp.int32(i), w]),
            hw,
        )

    return lax.cond(due, check, lambda hw: hw, hw)


def report_from_carry(hw, u=None, m=None) -> HealthReport:
    """Host-side report from a fetched in-carry word (and optional factor
    stats when the caller still holds the device arrays)."""
    import numpy as np

    hw = np.asarray(hw)
    it, word = int(hw[0]), int(hw[1])
    stats = {}
    if word and u is not None and m is not None:
        nu, nm = (float(x) for x in np.asarray(health_stats(u, m)))
        stats = {"max_row_norm_u": nu, "max_row_norm_m": nm}
    return HealthReport(iteration=it if word else -1, word=word, stats=stats)
