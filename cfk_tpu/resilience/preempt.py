"""Preemption tolerance: eviction signals and dead-collective watchdogs.

The dominant real-world failure for a TPU training fleet is infrastructure,
not math: preempted VMs (SIGTERM with a short grace window), killed workers
(SIGKILL — no warning at all), and checkpoint writes that stall the device.
ALX (arXiv:2112.02194) reports that at production scale the ALS job's
wall-clock is bounded by surviving preemptions between epochs.  Two small
host-side tools make the stepped training loops survive both:

- ``PreemptionGuard`` — a context manager that installs SIGTERM/SIGINT
  handlers setting a flag the resilient loops poll between iterations.  On
  eviction the loop drains the async checkpoint writer, commits one final
  checkpoint (skipped if the state just failed its health probe — a bad
  state is never committed, even under eviction), notes the preemption in
  the metrics, and returns resumable.  Handlers are restored on exit, and a
  second delivery of the same signal chains to the previous handler so a
  double Ctrl-C still kills a stuck process.  Under multi-process JAX every
  process polls the same iteration boundary, so the final save's collectives
  (the host gather) pair up across hosts; rank 0 writes the manifest.

- ``StallWatchdog`` — a monitor thread armed around the training loop and
  ticked once per completed iteration.  A SIGKILL'd peer leaves the
  survivors blocked inside a collective (C++ with the GIL released, so this
  thread still runs); when no tick arrives within ``timeout_s`` the watchdog
  drains the checkpoint writer (best-effort, bounded) and ``os._exit``s with
  ``STALL_EXIT_CODE`` — the checkpoint store stays intact by construction
  (atomic per-step renames), so a supervisor restarts the fleet and training
  resumes from the last committed step.  Signal-safety rule: the watchdog
  never touches jax (the runtime is wedged in the dead collective); it only
  reads host state and the filesystem.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time

# The survivor's "I detected a dead collective and left an intact
# checkpoint behind" exit status — distinct from crash codes so drivers
# (tests/test_multihost.py drills, supervisors) can tell a clean stall
# exit from a wreck.
STALL_EXIT_CODE = 17


class PreemptionGuard:
    """Install SIGTERM/SIGINT handlers that request a graceful save+exit.

    Usage::

        with PreemptionGuard() as guard:
            train_als(ds, cfg, checkpoint_manager=mgr,
                      preemption_guard=guard)
        if guard.triggered:
            ...  # exited resumable; re-launch to continue

    The handler only sets a flag (async-signal-safe by construction: no
    allocation, no locks, no jax); the stepped loops poll ``triggered``
    between iterations.  ``trigger()`` lets tests and chaos scenarios fire
    the guard without delivering a real signal.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self.signum: int | None = None
        self._previous: dict[int, object] = {}
        self._installed = False

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    @property
    def signal_name(self) -> str:
        if self.signum is None:
            return "manual"
        try:
            return signal.Signals(self.signum).name
        except ValueError:  # pragma: no cover - unknown signum
            return str(self.signum)

    def trigger(self, signum: int | None = None) -> None:
        """Request a graceful stop as if the signal had been delivered."""
        self.signum = signum if signum is not None else self.signum
        self._event.set()
        # Flight-record the request (trigger() is the test/chaos entry —
        # the real signal handler stays flag-only by the async-signal-
        # safety rule; the resilient loops record the delivery when they
        # poll the flag at the next boundary).
        from cfk_tpu.telemetry.recorder import record_event

        record_event("signal", "preemption_requested",
                     signal=self.signal_name)

    def _handler(self, signum, frame):
        if self._event.is_set():
            # Second delivery: the operator (or the platform) is insisting.
            # Chain to the pre-guard behavior so a wedged loop can still be
            # killed the ordinary way.
            prev = self._previous.get(signum)
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)
            return
        self.signum = signum
        self._event.set()

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError(
                "PreemptionGuard must be entered from the main thread "
                "(signal handlers can only be installed there)"
            )
        for s in self.signals:
            self._previous[s] = signal.getsignal(s)
            signal.signal(s, self._handler)
        self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if not self._installed:
            return
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):  # pragma: no cover
                pass
        self._previous.clear()
        self._installed = False


class StallWatchdog:
    """Exit with an intact checkpoint when iterations stop completing.

    Armed by the resilient loop around its stepped body and ticked once per
    completed iteration.  ``timeout_s`` bounds how long a collective may
    block before the process gives up on its peers: on expiry the watchdog
    runs ``on_stall`` (if any), drains the checkpoint manager's async
    writer with a bounded wait, prints one diagnostic line, and
    ``os._exit(exit_code)`` — ``sys.exit`` would merely raise in this
    thread while the main thread stays wedged in the dead collective.

    ``manager`` is drained, never written: the last committed step is the
    resume point (a mid-stall save of sharded device state would itself
    need the dead collective).  ``tick`` may be overridden/wrapped by
    drivers that want per-iteration progress reporting.

    jit trace+compile produces no ticks but is not a stall: the window is
    widened to ``compile_grace_s`` from ``arm()`` until the first tick,
    and again whenever the loop rebuilds its step (``extend_grace()`` —
    each escalation rung re-traces); a tick restores the normal
    ``timeout_s`` window.
    """

    def __init__(
        self,
        timeout_s: float,
        *,
        manager=None,
        on_stall=None,
        exit_code: int = STALL_EXIT_CODE,
        drain_timeout_s: float = 30.0,
        compile_grace_s: float | None = None,
    ) -> None:
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.compile_grace_s = (
            max(float(timeout_s), 60.0)
            if compile_grace_s is None else float(compile_grace_s)
        )
        self.manager = manager
        self.on_stall = on_stall
        self.exit_code = exit_code
        self.drain_timeout_s = drain_timeout_s
        self.last_tick: float | None = None
        self.last_done: int | None = None
        self.stalled = False
        self._window = self.compile_grace_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def arm(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._window = self.compile_grace_s  # first step includes compile
        self.last_tick = time.monotonic()
        self._thread = threading.Thread(
            target=self._watch, name="cfk-stall-watchdog", daemon=True
        )
        self._thread.start()

    def tick(self, done: int | None = None) -> None:
        self._window = self.timeout_s
        self.last_tick = time.monotonic()
        if done is not None:
            self.last_done = done

    def extend_grace(self) -> None:
        """Widen the window for a step rebuild (escalation re-trace)."""
        self._window = self.compile_grace_s
        self.last_tick = time.monotonic()

    def disarm(self) -> None:
        self._stop.set()

    def __enter__(self) -> "StallWatchdog":
        self.arm()
        return self

    def __exit__(self, *exc) -> None:
        self.disarm()

    def _watch(self) -> None:
        while not self._stop.wait(min(self.timeout_s / 4.0, 1.0)):
            last = self.last_tick
            if last is None:
                continue
            if time.monotonic() - last > self._window:
                self.stalled = True
                self._stall_exit()
                return

    def _stall_exit(self) -> None:  # pragma: no cover - exercised via drills
        try:
            # Flight-record the stall before exiting: the dump's tail is
            # the last iterations this process completed before its peer
            # died (host-only work — the rule about never touching the
            # wedged jax runtime holds).
            from cfk_tpu.telemetry.recorder import dump_flight, record_event

            record_event("fault", "stall_watchdog", last_done=self.last_done,
                         timeout_s=self.timeout_s)
            dump_flight("stall_watchdog")
        except Exception:
            pass
        try:
            if self.on_stall is not None:
                self.on_stall(self)
        except Exception:
            pass
        try:
            if self.manager is not None and hasattr(self.manager,
                                                    "wait_pending"):
                self.manager.wait_pending(timeout=self.drain_timeout_s)
        except Exception:
            pass
        try:
            print(
                f"STALL_WATCHDOG no iteration completed in "
                f"{self.timeout_s:.1f}s (last completed iteration: "
                f"{self.last_done}); assuming a dead collective peer — "
                f"exiting {self.exit_code} with the checkpoint store "
                "intact",
                file=sys.stderr,
                flush=True,
            )
        except Exception:
            pass
        os._exit(self.exit_code)
