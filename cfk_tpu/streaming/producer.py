"""Producer side of the streaming-update pipeline: ratings → durable log.

Rating upserts append to the ``rating-updates`` topic as ``RatingUpdate``
frames (``cfk_tpu.transport.serdes``), keyed by user id under the same
mod-N ``PureModPartitioner`` rule as ingest — so a user's updates always
land on ONE partition and per-user ordering is the partition's offset
order.  On a durable transport (``FileBroker``, a TCP broker) the topic IS
the system of record: the consumer's crash recovery replays it from the
committed cursor, and a full retrain can always be rebuilt from base data
plus the whole log.

``seq`` numbers are producer-assigned and strictly increasing; they make
re-rates (two updates to the same (user, movie) cell) and retried appends
idempotent on the consumer — last-seq-wins, equal-seq drops.  On
construction against an existing topic the producer resumes past the
highest seq already in the log (one tail frame per partition; a single
logical producer at a time is assumed, like the reference's one
``NetflixDataFormatProducer``).
"""

from __future__ import annotations

import numpy as np

from cfk_tpu.transport.broker import Transport, mod_partition
from cfk_tpu.transport.serdes import RatingUpdate, encode_rating_update

UPDATES_TOPIC = "rating-updates"


def ensure_updates_topic(
    transport: Transport, topic: str = UPDATES_TOPIC, num_partitions: int = 1
) -> int:
    """Create the updates topic if absent; returns its partition count.

    An existing topic keeps its own partition count (the cursor layout
    committed with the factors depends on it, so re-partitioning a live
    topic is refused the same way the reference's ``setup.sh`` re-provisions
    out-of-band)."""
    try:
        return transport.num_partitions(topic)
    except KeyError:
        transport.create_topic(topic, num_partitions)
        return num_partitions


class StreamProducer:
    """Append rating upserts to the updates topic with monotone seq numbers."""

    def __init__(
        self,
        transport: Transport,
        *,
        topic: str = UPDATES_TOPIC,
        num_partitions: int = 1,
    ) -> None:
        self.transport = transport
        self.topic = topic
        self.num_partitions = ensure_updates_topic(
            transport, topic, num_partitions
        )
        self._next_seq = self._resume_seq()

    def _resume_seq(self) -> int:
        """Highest seq in the log + 1 (0 on a fresh topic).

        One frame read per partition: a single producer appends seqs in
        order, so each partition's LAST record carries its partition max.
        """
        from cfk_tpu.transport.serdes import decode_rating_update

        high = -1
        for p in range(self.num_partitions):
            end = self.transport.end_offset(self.topic, p)
            if end == 0:
                continue
            for rec in self.transport.consume(self.topic, p, start_offset=end - 1):
                high = max(high, decode_rating_update(rec.value).seq)
        return high + 1

    @property
    def next_seq(self) -> int:
        return self._next_seq

    def send(self, user: int, movie: int, rating: float) -> int:
        """Append one upsert; returns the seq it was assigned."""
        if user < 0 or movie < 0:
            raise ValueError(
                f"user/movie ids must be non-negative raw ids, got "
                f"({user}, {movie})"
            )
        seq = self._next_seq
        self._next_seq += 1
        self.transport.produce(
            self.topic,
            key=int(user) % (1 << 31),  # partition key must fit int32
            value=encode_rating_update(
                RatingUpdate(seq=seq, user=int(user), movie=int(movie),
                             rating=float(rating))
            ),
            partition=mod_partition(int(user), self.num_partitions),
        )
        return seq

    def send_many(self, users, movies, ratings) -> int:
        """Bulk append of parallel (user, movie, rating) arrays.

        Returns the first seq of the run (they are assigned contiguously in
        array order — the array order IS the stream's logical time).  Uses
        the transport's bulk frame path per partition when available
        (``FileBroker.produce_frames``), so synthetic bench streams of 100k
        updates don't pay a Python loop of fsync'd appends.
        """
        users = np.asarray(users, np.int64)
        movies = np.asarray(movies, np.int64)
        ratings = np.asarray(ratings, np.float32)
        n = users.shape[0]
        if movies.shape != (n,) or ratings.shape != (n,):
            raise ValueError(
                f"parallel arrays required, got {users.shape}/"
                f"{movies.shape}/{ratings.shape}"
            )
        if n == 0:
            return self._next_seq
        if users.min() < 0 or movies.min() < 0:
            raise ValueError("user/movie ids must be non-negative raw ids")
        first = self._next_seq
        seqs = first + np.arange(n, dtype=np.int64)
        self._next_seq = first + n
        parts = (users % self.num_partitions).astype(np.int64)
        fast = getattr(self.transport, "produce_frames", None)
        for p in range(self.num_partitions):
            sel = np.nonzero(parts == p)[0]  # stable: preserves seq order
            if sel.size == 0:
                continue
            if fast is not None:
                frames = np.zeros((sel.size, 28), np.uint8)
                frames[:, 0:8] = seqs[sel].astype(">i8").view(np.uint8).reshape(-1, 8)
                frames[:, 8:16] = users[sel].astype(">i8").view(np.uint8).reshape(-1, 8)
                frames[:, 16:24] = movies[sel].astype(">i8").view(np.uint8).reshape(-1, 8)
                frames[:, 24:28] = ratings[sel].astype(">f4").view(np.uint8).reshape(-1, 4)
                fast(self.topic, users[sel] % (1 << 31), frames, p)
            else:
                for i in sel.tolist():
                    self.transport.produce(
                        self.topic,
                        key=int(users[i]) % (1 << 31),
                        value=encode_rating_update(RatingUpdate(
                            seq=int(seqs[i]), user=int(users[i]),
                            movie=int(movies[i]), rating=float(ratings[i]),
                        )),
                        partition=p,
                    )
        return first
