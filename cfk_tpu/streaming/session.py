"""The rate → fold-in loop: exactly-once streaming updates into live factors.

``StreamSession`` closes the loop the reference only sketched: ratings
arrive continuously on a durable updates topic, micro-batches of touched
users are folded into the live factor state by one restricted ALS
half-iteration, and every commit persists the factors ATOMICALLY WITH the
consumer's offset cursor — the cursor rides the checkpoint manifest
(``CheckpointManager.save(meta=...)``), whose atomic directory rename plus
crc32 verification the PR 3/5 machinery already proves out.  There is no
instant at which the factors and the cursor can disagree on disk; a crash
replays exactly the uncommitted log suffix, and because micro-batch
boundaries are log offsets (``StreamConsumer``), the replayed batches —
and therefore the recovered factors — are bit-identical to an
uninterrupted run.

Delivery semantics, layer by layer:

- **transport** may drop / duplicate / reorder (at-least-once):
  ``StreamConsumer`` heals all three by offset — a batch is a pure
  function of the log.
- **log** may hold retried appends and re-rates: ``StreamState`` dedups by
  (user, movie) seq, last-seq-wins — application is idempotent.
- **math** may be poisoned (singular systems at λ=0, NaN ratings): every
  fold-in is probed by the PR 3 health sentinel BEFORE commit; a tripped
  batch is rolled back (staged state discarded, factors untouched) and the
  recovery ladder escalates (λ bump → split epilogue → GJ) on retry;
  a batch that defeats the whole ladder is quarantined — its offsets are
  consumed (poison must not wedge the stream) but its writes never reach
  the served factors or the state.
- **process** may be evicted: the ``PreemptionGuard`` is polled at batch
  boundaries; eviction drains the async checkpoint writer so the last
  factor+cursor commit is durably on disk, then returns resumable.

Periodic warm-started full retrains (``retrain_every``) rebuild the full
dataset from the merged state and run the resilient stepped training loop
with the CURRENT factors as the starting checkpoint, folding the movie
side's staleness back in without ever serving a cold model.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cfk_tpu.resilience import sentinel as _sentinel
from cfk_tpu.resilience.loop import drain_checkpoints, save_checkpoint
from cfk_tpu.resilience.policy import Overrides, RecoveryPolicy, policy_from_config
from cfk_tpu.streaming.consumer import StreamConsumer
from cfk_tpu.streaming.foldin import fold_in_rows
from cfk_tpu.streaming.producer import UPDATES_TOPIC
from cfk_tpu.streaming.state import StreamState
from cfk_tpu.telemetry import record_event, span
from cfk_tpu.telemetry.recorder import dump_flight

_STREAM_MODEL = "als-stream"


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Knobs of the streaming loop (model/solver knobs stay on ALSConfig)."""

    topic: str = UPDATES_TOPIC
    # Log records consumed per partition per micro-batch.  Batch boundaries
    # are offsets, so this value is part of the replay contract: it is
    # recorded in every commit and the committed value wins on resume (a
    # changed setting applies only to batches past the committed cursor).
    batch_records: int = 256
    # Fold-in solve layout: "padded" | "tiled" | "auto" (= tiled when the
    # training config's layout is tiled — the same kernels as training —
    # else padded).
    foldin_layout: str = "auto"
    # Warm full retrain every N stream commits (None = never): rebuild the
    # dataset from the merged state and run the resilient training loop
    # warm-started from the current factors.
    retrain_every: int | None = None
    # Re-poll budget for delivery gaps (dropped records must be redelivered
    # by the at-least-once transport; after this many re-polls the session
    # fails loudly instead of hanging like the reference).
    gap_retries: int = 20
    gap_wait_s: float = 0.05
    # Sleep between polls while following an idle topic.
    poll_wait_s: float = 0.05
    # User-table growth quantum: new streamed-in users extend the factor
    # table in chunks of this many rows (bounds re-jits and reallocations).
    grow_multiple: int = 64

    def __post_init__(self) -> None:
        if self.batch_records < 1:
            raise ValueError(
                f"batch_records must be >= 1, got {self.batch_records}"
            )
        if self.foldin_layout not in ("auto", "padded", "tiled"):
            raise ValueError(
                f"foldin_layout must be auto/padded/tiled, got "
                f"{self.foldin_layout!r}"
            )
        if self.retrain_every is not None and self.retrain_every < 1:
            raise ValueError(
                f"retrain_every must be >= 1, got {self.retrain_every}"
            )
        if self.grow_multiple < 1:
            raise ValueError(
                f"grow_multiple must be >= 1, got {self.grow_multiple}"
            )


class PoisonedBatchError(RuntimeError):
    """Raised when ``on_unrecoverable='raise'`` and a batch defeats the
    whole recovery ladder."""


class StreamSession:
    """Consume rating updates and fold them into live ALS factors.

    ``manager`` (a ``CheckpointManager``-shaped store) is the session's
    system of record: factors + offset cursor + stream metadata commit as
    one atomic step per micro-batch.  On construction the session either
    resumes from the store's newest intact step (rebuilding the rating
    state by replaying the log below the committed cursor) or bootstraps
    from ``base_model`` (committing step 0 with a zero cursor).
    """

    def __init__(
        self,
        dataset,
        config,
        transport,
        manager,
        *,
        stream: StreamConfig | None = None,
        base_model=None,
        metrics=None,
        preemption_guard=None,
        policy: RecoveryPolicy | None = None,
    ) -> None:
        from cfk_tpu.config import enable_compile_cache
        from cfk_tpu.utils.metrics import Metrics

        if manager is None:
            raise ValueError(
                "StreamSession needs a checkpoint manager: the offset "
                "cursor commits atomically with the factors, so a durable "
                "store is not optional"
            )
        # Before the first compile (ISSUE 13): a warm persistent cache is
        # what makes a cold fold-in process skip the re-COMPILE half of
        # the per-batch trace bound; prewarm() covers the trace half.
        enable_compile_cache(getattr(config, "compile_cache_dir", None))
        self.dataset = dataset
        self.config = config
        self.transport = transport
        self.manager = manager
        self.stream = stream or StreamConfig()
        self.metrics = metrics if metrics is not None else Metrics()
        self.guard = preemption_guard
        self.policy = policy or policy_from_config(config)
        self.health = _sentinel.health_from_config(config)
        self._layout = (
            self.stream.foldin_layout if self.stream.foldin_layout != "auto"
            else ("tiled" if config.layout == "tiled" else "padded")
        )
        # Out-of-core sessions (ISSUE 19): with offload_tier='host_window'
        # the movie table lives in a host-resident ``HostFactorStore``
        # (the user table always was host numpy) and every fold-in stages
        # the batch's touched movie rows as one ad-hoc window
        # (``foldin.fold_in_rows_windowed`` — bit-identical rows).  The
        # commit protocol, sentinel ladder, and quarantine semantics below
        # are UNCHANGED: they only ever see the solved rows and the
        # factor arrays at commit time.
        self._offload = (
            getattr(config, "offload_tier", "device") == "host_window"
        )
        self._m_store = None
        self._foldin_stats: dict = {}
        if self._offload:
            if self.stream.foldin_layout == "tiled":
                raise ValueError(
                    "foldin_layout='tiled' needs the device-resident "
                    "movie table; an offload_tier='host_window' session "
                    "stages ad-hoc windows (foldin_layout 'auto'/'padded')"
                )
            self._layout = "padded"
        self._overrides = Overrides(
            lam=config.lam, fused_epilogue=config.fused_epilogue,
            reg_solve_algo=(None if config.reg_solve_algo == "auto"
                            else config.reg_solve_algo),
        )
        self.state = StreamState(dataset)
        self.stream_step = 0
        self.quarantined: list[dict] = []
        self._m = None  # jnp [M_pad, k], fixed between retrains
        self._u = None  # np [U_pad, k], row-mutated by fold-ins
        # Serving-side subscribers (ISSUE 8): fired AFTER each durable
        # commit with copies of the solved rows, so a hot-user factor
        # cache (serving.ServeEngine.attach_session) re-serves fold-in
        # updates without ever reading this session's mutable arrays.
        self._commit_listeners: list = []
        resumed = self._try_resume()
        if not resumed:
            self._bootstrap(base_model)

    # -- bootstrap / resume --------------------------------------------------

    def _factor_dtype(self):
        import jax.numpy as jnp

        return jnp.dtype(self.config.dtype)

    def _set_movie(self, arr) -> None:
        """Install the fixed movie table: a device array normally, a
        host ``HostFactorStore`` in offload mode — SAME bytes either way
        (the store holds the config dtype verbatim), so the staged
        fold-in windows read exactly what the resident path would."""
        import jax.numpy as jnp

        if self._offload:
            from cfk_tpu.offload.store import HostFactorStore

            self._m_store = HostFactorStore.from_array(
                np.asarray(arr), dtype=self.config.dtype
            )
            self._m = None
        else:
            self._m = jnp.asarray(np.asarray(arr),
                                  dtype=self._factor_dtype())

    def _bootstrap(self, base_model) -> None:
        if base_model is None:
            raise ValueError(
                "no resumable stream state in the checkpoint store and no "
                "base_model given — train a base model first (train_als) "
                "or point the session at its existing stream directory"
            )
        dt = self._factor_dtype()
        self._u = np.asarray(base_model.user_factors).astype(dt)
        self._set_movie(base_model.movie_factors)
        nparts = self.transport.num_partitions(self.stream.topic)
        self.consumer = StreamConsumer(
            self.transport, topic=self.stream.topic,
            cursors={p: 0 for p in range(nparts)},
            gap_retries=self.stream.gap_retries,
            gap_wait_s=self.stream.gap_wait_s,
        )
        # Step 0 pins the zero cursor atomically with the base factors, so
        # even a crash before the first batch resumes cleanly.
        self._commit(note="bootstrap")

    def _try_resume(self) -> bool:
        latest = self.manager.latest_valid_iteration()
        if latest is None:
            return False
        st = self.manager.restore(latest)
        meta = st.meta
        if meta.get("model") != _STREAM_MODEL:
            raise ValueError(
                f"checkpoint store holds model={meta.get('model')!r}, not a "
                f"{_STREAM_MODEL} session — point the stream at its own "
                "directory"
            )
        if int(meta.get("rank", -1)) != self.config.rank:
            raise ValueError(
                f"stream checkpoint has rank {meta.get('rank')}, config "
                f"wants {self.config.rank}"
            )
        if int(meta.get("base_users", -1)) != self.state.num_base_users:
            raise ValueError(
                "stream checkpoint was committed against a base dataset "
                f"with {meta.get('base_users')} users; this dataset has "
                f"{self.state.num_base_users} — same --data required to "
                "resume (the rating state replays from it)"
            )
        dt = self._factor_dtype()
        self._u = np.asarray(st.user_factors).astype(dt)
        self._set_movie(st.movie_factors)
        self.stream_step = int(meta.get("stream_step", latest))
        self.quarantined = list(meta.get("quarantined", []))
        ov = meta.get("overrides")
        if ov is not None:
            # restore the sticky escalation ladder state committed with
            # the factors — resuming at the config's un-escalated knobs
            # would solve post-crash batches differently from the
            # uninterrupted run (bit-exact replay contract)
            self._overrides = Overrides(
                lam=float(ov["lam"]),
                fused_epilogue=ov.get("fused_epilogue"),
                reg_solve_algo=ov.get("reg_solve_algo"),
            )
        # Batch boundaries are part of the replay contract: the committed
        # batch_records wins over this session's setting, so post-cursor
        # batches are re-cut exactly as an uninterrupted run would have
        # cut them (batch composition moves the solved rows at the ulp
        # level — foldin.py's determinism contract).
        committed_br = int(meta.get("batch_records",
                                    self.stream.batch_records))
        if committed_br != self.stream.batch_records:
            self.metrics.note(
                "batch_records_override",
                f"resume uses the committed batch_records={committed_br} "
                f"(this session asked for {self.stream.batch_records}; the "
                "replay contract pins the committed value)",
            )
            self.stream = dataclasses.replace(
                self.stream, batch_records=committed_br
            )
        cursors = {int(p): int(o) for p, o in meta.get("offsets", {}).items()}
        self.consumer = StreamConsumer(
            self.transport, topic=self.stream.topic, cursors=cursors,
            gap_retries=self.stream.gap_retries,
            gap_wait_s=self.stream.gap_wait_s,
        )
        self._replay_state(cursors, meta)
        self.metrics.note(
            "stream_resumed",
            f"step {self.stream_step}, cursor {cursors}, "
            f"{len(meta.get('new_users', []))} streamed-in users",
        )
        record_event("stream", "stream_resumed", step=self.stream_step)
        return True

    def _replay_state(self, cursors: dict[int, int], meta: dict) -> None:
        """Rebuild the rating state = base + log[0, committed cursor).

        Only the STATE is replayed (dedup + upserts) — no solving; the
        factors came from the checkpoint.  New-user rows are pre-assigned
        from the committed order, so the rebuilt rows line up with the
        checkpointed factor rows regardless of how this replay chunks the
        log (live runs interleave partitions batch by batch; the replay
        need not re-cut those boundaries just to rebuild a
        composition-independent state).  QUARANTINED offset ranges (poison
        batches whose offsets were consumed but whose writes never reached
        the state) are recorded in every commit and skipped here — the
        state must stay a pure function of the log MINUS the quarantine,
        or resume would re-apply the very writes the ladder rejected.
        """
        for i, raw in enumerate(meta.get("new_users", [])):
            self.state._new_user_rows[int(raw)] = self.state.num_base_users + i
            self.state._new_user_raw.append(int(raw))
        skip: dict[int, list[tuple[int, int]]] = {}
        for q in self.quarantined:
            for p, (qlo, qhi) in q.get("offsets", {}).items():
                skip.setdefault(int(p), []).append((int(qlo), int(qhi)))
        replay = StreamConsumer(
            self.transport, topic=self.stream.topic,
            cursors={p: 0 for p in cursors},
            gap_retries=self.stream.gap_retries,
            gap_wait_s=self.stream.gap_wait_s,
        )
        applied = 0
        for p, hi in sorted(cursors.items()):
            lo = 0
            while lo < hi:
                take = min(hi - lo, 1 << 14)
                values, _, _ = replay._collect_range(p, lo, lo + take)
                ranges = skip.get(p, ())
                values = [
                    v for i, v in enumerate(values)
                    if not any(qlo <= lo + i < qhi for qlo, qhi in ranges)
                ]
                from cfk_tpu.transport.serdes import decode_rating_update

                pending = self.state.stage(
                    [decode_rating_update(v) for v in values]
                )
                if pending.new_user_raw:
                    raise ValueError(
                        "stream checkpoint's new-user list does not cover "
                        f"raw ids {pending.new_user_raw[:4]} found below "
                        "the committed cursor — store and log disagree"
                    )
                self.state.commit(pending)
                applied += pending.stats.fresh
                lo += take
        if self.state.num_users != int(meta.get("users",
                                                self.state.num_users)):
            raise ValueError(
                f"replayed state has {self.state.num_users} users, commit "
                f"recorded {meta.get('users')} — store and log disagree"
            )
        self.metrics.incr("replayed_updates", applied)

    # -- the loop ------------------------------------------------------------

    @property
    def user_factors(self) -> np.ndarray:
        return self._u

    @property
    def movie_factors(self):
        if self._offload:
            return self._m_store.as_array()
        return self._m

    def model(self):
        """Current live factors as an ``ALSModel`` (serving view).  An
        offload session returns host arrays (materializing the store is
        the caller's choice — the session itself never holds the full
        movie table on device)."""
        import jax.numpy as jnp

        from cfk_tpu.models.als import ALSModel

        if self._offload:
            return ALSModel(
                user_factors=self._u,
                movie_factors=self._m_store.as_array(),
                num_users=self.state.num_users,
                num_movies=self.state.num_movies,
            )
        return ALSModel(
            user_factors=jnp.asarray(self._u),
            movie_factors=self._m,
            num_users=self.state.num_users,
            num_movies=self.state.num_movies,
        )

    def backlog(self) -> int:
        return self.consumer.backlog()

    def _grow_users(self, num_users: int) -> None:
        """Extend the user factor table for streamed-in new users."""
        need = num_users
        have = self._u.shape[0]
        if need <= have:
            return
        quantum = self.stream.grow_multiple
        target = ((need + quantum - 1) // quantum) * quantum
        grown = np.zeros((target, self._u.shape[1]), dtype=self._u.dtype)
        grown[:have] = self._u
        self._u = grown

    def _solve_pending(self, pending, overrides: Overrides):
        """Fold-in solve of one staged batch under the given overrides;
        returns (rows [T, k] f32, probe word int)."""
        import jax.numpy as jnp

        neighbor_data = [
            self.state.neighbors(row, pending.cell_writes.get(row))
            for row in pending.touched_rows
        ]
        staged = None
        with self.metrics.phase("foldin_solve"), \
                span("stream/batch/solve", touched=len(neighbor_data),
                     offload=int(self._offload)):
            if self._offload:
                from cfk_tpu.streaming.foldin import fold_in_rows_windowed

                rows, staged = fold_in_rows_windowed(
                    self._m_store, neighbor_data,
                    lam=overrides.lam,
                    solver=self.config.solver,
                    pad_multiple=self.config.pad_multiple,
                    reg_solve_algo=overrides.reg_solve_algo,
                    stats=self._foldin_stats,
                    return_staged=True,
                )
                self.metrics.gauge(
                    "foldin_windows_staged",
                    self._foldin_stats.get("foldin_windows_staged", 0))
                self.metrics.gauge(
                    "foldin_staged_mb",
                    round(self._foldin_stats.get(
                        "foldin_staged_bytes", 0) / 1e6, 3))
            else:
                rows = fold_in_rows(
                    self._m, neighbor_data,
                    lam=overrides.lam,
                    solver=self.config.solver,
                    layout=self._layout,
                    pad_multiple=self.config.pad_multiple,
                    fused_epilogue=overrides.fused_epilogue,
                    in_kernel_gather=self.config.in_kernel_gather,
                    reg_solve_algo=overrides.reg_solve_algo,
                )
        word = 0
        if self.health is not None and rows.shape[0]:
            with self.metrics.phase("health_check"), \
                    span("stream/batch/probe"):
                # Offload mode probes the STAGED window — the fixed rows
                # the solve actually read — instead of the full table the
                # session no longer holds on device; the sentinel bitmask
                # semantics (non-finite / norm) are unchanged.
                m_probe = staged if self._offload else self._m
                word = int(np.asarray(_sentinel.probe_word(
                    jnp.asarray(rows), m_probe, self.health.norm_limit
                )))
            self.metrics.incr("health_checks")
        return rows, word

    def prewarm(self, *, max_touched: int | None = None,
                max_width: int | None = None) -> dict:
        """Trace the fold-in pow2 bucket grid up front (ISSUE 13).

        The solve shapes a live stream produces are bounded: touched
        users bucket to ``_pow2_ceil(t, 8)`` up to ``batch_records`` and
        rectangle widths to pow2 multiples of ``pad_multiple`` up to the
        heaviest neighbor list.  Walking that grid once with synthetic
        zero batches compiles every program a cold process would
        otherwise trace mid-stream — the ROADMAP-measured fold-in bound
        ("per-batch jit re-trace dominates") paid at startup instead of
        against live updates (and not at all on a warm restart when
        ``ALSConfig.compile_cache_dir`` is wired — the persistent cache
        serves each compile).  Results are discarded; the jit cache keys
        on shapes, so the stream's bits are untouched.

        Covers the PADDED fold layout (the micro-batch default).  Tiled
        fold-in block statics are data-dependent (chunk cuts follow the
        batch's actual neighbor lists), so a tiled-layout session
        returns ``{"skipped": ...}`` — its first-batch compile is
        bounded by the compile cache instead.

        Returns ``{"programs", "new_traces", "prewarm_s"}``; serving a
        first real batch inside the warmed grid afterwards traces
        nothing (``tests/test_staging.py`` pins it)."""
        with span("stream/prewarm"):
            return self._prewarm_impl(max_touched=max_touched,
                                      max_width=max_width)

    def _prewarm_impl(self, *, max_touched: int | None = None,
                      max_width: int | None = None) -> dict:
        import time as _time

        from cfk_tpu.streaming.foldin import _pow2_ceil, trace_count

        t0 = _time.time()
        if self._offload:
            note = ("skipped: offload fold-in programs key on the staged "
                    "window's pow2 row bucket (data-dependent); rely on "
                    "compile_cache_dir")
            self.metrics.note("prewarm", note)
            return {"programs": 0, "new_traces": 0, "prewarm_s": 0.0,
                    "skipped": note}
        if self._layout != "padded":
            note = ("skipped: tiled fold-in block statics are "
                    "data-dependent; rely on compile_cache_dir")
            self.metrics.note("prewarm", note)
            return {"programs": 0, "new_traces": 0, "prewarm_s": 0.0,
                    "skipped": note}
        mt = max(int(max_touched or self.stream.batch_records), 1)
        if max_width is None:
            counts = np.asarray(self.dataset.user_blocks.count)
            max_width = max(int(counts.max()) if counts.size else 1, 1)
        pm = max(self.config.pad_multiple, 1)
        widths = []
        p = _pow2_ceil(1, pm)
        while True:
            widths.append(p)
            if p >= max_width:
                break
            p *= 2
        ents = []
        e = _pow2_ceil(1, 8)
        while True:
            ents.append(e)
            if e >= mt:
                break
            e *= 2
        before = trace_count()
        programs = 0
        num_m = int(self._m.shape[0])
        for e in ents:
            for p in widths:
                # One user at the full width pins the rectangle to
                # exactly (e, p); movie rows are valid table rows,
                # ratings zero — the solved values are discarded.
                wide = (np.minimum(np.arange(p), num_m - 1)
                        .astype(np.int32),
                        np.zeros(p, np.float32))
                thin = (np.zeros(1, np.int32), np.zeros(1, np.float32))
                fold_in_rows(
                    self._m, [wide] + [thin] * (e - 1),
                    lam=self._overrides.lam,
                    solver=self.config.solver,
                    layout="padded",
                    pad_multiple=self.config.pad_multiple,
                    fused_epilogue=self._overrides.fused_epilogue,
                    in_kernel_gather=self.config.in_kernel_gather,
                    reg_solve_algo=self._overrides.reg_solve_algo,
                )
                programs += 1
        out = {
            "programs": programs,
            "new_traces": trace_count() - before,
            "prewarm_s": round(_time.time() - t0, 4),
        }
        self.metrics.gauge("prewarm_programs", programs)
        self.metrics.gauge("prewarm_new_traces", out["new_traces"])
        self.metrics.gauge("prewarm_s", out["prewarm_s"])
        return out

    def _commit(self, note: str | None = None) -> None:
        meta = {
            "model": _STREAM_MODEL,
            "rank": int(self.config.rank),
            "num_shards": 1,
            "stream_step": self.stream_step,
            "offsets": {str(p): int(o)
                        for p, o in self.consumer.cursors.items()},
            "batch_records": self.stream.batch_records,
            "seq_high": int(self.state.applied_seq_high),
            "base_users": self.state.num_base_users,
            "users": self.state.num_users,
            "new_users": [int(r) for r in self.state._new_user_raw],
            # poison ranges whose offsets are consumed but whose writes
            # must never be re-applied — crash replay skips them
            "quarantined": self.quarantined,
            # the sticky escalation state: post-resume batches must solve
            # under the same overrides an uninterrupted run would have
            # used, or replay is no longer bit-identical (a stream that
            # needed λ·10 once needs it after the crash too)
            "overrides": {
                "lam": float(self._overrides.lam),
                "fused_epilogue": self._overrides.fused_epilogue,
                "reg_solve_algo": self._overrides.reg_solve_algo,
            },
        }
        if note:
            meta["note"] = note
        with self.metrics.phase("commit"), \
                span("stream/batch/commit", step=self.stream_step):
            save_checkpoint(
                self.manager, self.stream_step, self._u,
                np.asarray(self.movie_factors), meta=meta,
            )
        self.metrics.incr("stream_commits")
        record_event("stream", "commit", step=self.stream_step,
                     note=note or "")

    def add_commit_listener(self, fn) -> None:
        """Subscribe ``fn(event: dict)`` to every durable commit.

        The event carries COPIES (never views of this session's mutable
        state): ``touched_rows`` + ``rows`` [T, k] f32 (the freshly solved
        factor rows), ``cells`` [(user_row, movie_row), ...] (the rated
        cells the batch applied), ``num_users``, ``stream_step``; a warm
        retrain instead fires ``retrain=True`` with full ``user_factors``/
        ``movie_factors`` snapshots.  Fired AFTER the factor+cursor commit
        is handed to the (async) writer — a request served after the
        listener returns reflects the folded-in factors."""
        self._commit_listeners.append(fn)

    def _fire_commit(self, event: dict) -> None:
        event.setdefault("stream_step", self.stream_step)
        event.setdefault("num_users", self.state.num_users)
        for fn in self._commit_listeners:
            # A listener failure must not poison the commit that already
            # happened, nor starve the OTHER listeners (a broken serving
            # subscriber taking down the training stream would invert the
            # dependency) — record it loudly and keep going.
            try:
                fn(event)
            except Exception as e:
                self.metrics.incr("commit_listener_errors")
                record_event(
                    "stream", "commit_listener_error",
                    step=self.stream_step,
                    listener=getattr(fn, "__qualname__", repr(fn)),
                    error=f"{type(e).__name__}: {e}",
                )

    def step(self) -> dict | None:
        """Process ONE micro-batch; returns its summary, or None when
        caught up with the log."""
        batch = self.consumer.poll(self.stream.batch_records)
        if batch is None:
            return None
        with span("stream/batch", step=self.stream_step + 1,
                  records=batch.num_records):
            return self._step_batch(batch)

    def _step_batch(self, batch) -> dict:
        with self.metrics.phase("stage"), \
                span("stream/batch/stage", records=batch.num_records):
            pending = self.state.stage(batch.updates)
        self.metrics.incr("updates_fresh", pending.stats.fresh)
        self.metrics.incr("updates_stale", pending.stats.stale)
        self.metrics.incr("updates_unknown_movie", pending.stats.unknown_movie)
        if batch.duplicates_dropped:
            self.metrics.incr("delivery_duplicates", batch.duplicates_dropped)
            record_event("stream", "delivery_duplicates_dropped",
                         step=self.stream_step + 1,
                         duplicates=batch.duplicates_dropped)
        if batch.gap_repolls:
            self.metrics.incr("delivery_gap_repolls", batch.gap_repolls)
            record_event("stream", "delivery_gap_repolls",
                         step=self.stream_step + 1,
                         repolls=batch.gap_repolls)
        summary = {
            "records": batch.num_records,
            "fresh": pending.stats.fresh,
            "stale": pending.stats.stale,
            "touched_users": len(pending.touched_rows),
            "new_users": pending.stats.new_users,
            "quarantined": False,
            "trips": 0,
        }
        if pending.touched_rows:
            overrides = self._overrides
            trips = 0
            while True:
                rows, word = self._solve_pending(pending, overrides)
                if not word:
                    break
                trips += 1
                summary["trips"] = trips
                self.metrics.incr("health_trips")
                report = _sentinel.HealthReport(
                    iteration=self.stream_step + 1, word=word, stats={}
                )
                self.metrics.note(
                    f"stream_trip_{self.stream_step + 1}_{trips}",
                    report.summary(),
                )
                record_event("fault", "stream_trip",
                             step=self.stream_step + 1, trip=trips,
                             reason=report.summary())
                dump_flight(f"stream_trip_{self.stream_step + 1}_{trips}")
                if trips > self.policy.max_recoveries:
                    # The whole ladder lost: quarantine the batch — its
                    # offsets are consumed (a poison pill must not wedge
                    # the stream) but neither the factors nor the rating
                    # state ever see its writes.
                    msg = (
                        f"stream batch at step {self.stream_step + 1} "
                        f"defeated the recovery ladder ({report.summary()}); "
                        f"offsets {batch.cursors_before} → "
                        f"{batch.cursors_after} quarantined"
                    )
                    record_event("fault", "quarantine",
                                 step=self.stream_step + 1,
                                 reasons=report.reasons, detail=msg)
                    dump_flight("quarantine")
                    if self.policy.on_unrecoverable == "raise":
                        raise PoisonedBatchError(msg)
                    self.quarantined.append({
                        "stream_step": self.stream_step + 1,
                        "offsets": {str(p): [batch.cursors_before[p],
                                             batch.cursors_after[p]]
                                    for p in batch.cursors_after},
                        "reasons": report.reasons,
                    })
                    self.metrics.incr("quarantined_batches")
                    self.metrics.note("quarantined", msg)
                    import warnings

                    warnings.warn(msg)
                    summary["quarantined"] = True
                    pending = None
                    break
                # Rollback is free — nothing was committed — so a retry is
                # one escalation rung up (λ bump → split epilogue → GJ),
                # sticky for the rest of the session exactly like the
                # training ladder (a stream that needed λ·10 once will
                # need it again).
                new_overrides = self.policy.escalate(self._overrides,
                                                     trips + 1)
                if new_overrides != overrides:
                    overrides = new_overrides
                    self._overrides = new_overrides
                    self.metrics.gauge("stream_escalation_level", trips)
                    self.metrics.note(
                        f"stream_escalation_{trips}",
                        f"lam={overrides.lam:g} "
                        f"fused={overrides.fused_epilogue} "
                        f"algo={overrides.reg_solve_algo}",
                    )
                    record_event("fault", "stream_escalation", rung=trips,
                                 lam=overrides.lam)
            if pending is not None:
                self.state.commit(pending)
                self._grow_users(self.state.num_users)
                if pending.touched_rows:
                    self._u[np.asarray(pending.touched_rows)] = (
                        rows.astype(self._u.dtype)
                    )
        self.stream_step += 1
        self._commit()
        if pending is not None and pending.touched_rows:
            # publish the COMMITTED representation — read back from the
            # factor table AFTER the dtype cast, so a bf16-dtype session's
            # listeners cache exactly what a post-crash engine would
            # restore from the checkpoint (not the pre-cast f32 solve)
            touched_idx = np.asarray(pending.touched_rows)
            self._fire_commit({
                "touched_rows": [int(r) for r in pending.touched_rows],
                "rows": np.array(self._u[touched_idx], np.float32),
                "cells": [
                    (int(row), int(mv))
                    for row, overlay in pending.cell_writes.items()
                    for mv in overlay
                ],
                "retrain": False,
            })
        summary["stream_step"] = self.stream_step
        if (self.stream.retrain_every is not None
                and self.stream_step % self.stream.retrain_every == 0):
            self.retrain()
        return summary

    def run(self, *, max_batches: int | None = None, follow: bool = False,
            before_batch=None):
        """Drain (or follow) the updates topic; returns the live model.

        ``follow=True`` keeps polling an idle topic until ``max_batches``
        or eviction; the default drains until caught up.  ``before_batch``
        (chaos/testing hook) is called with the upcoming stream step before
        every poll — fault injectors deliver signals or kill the process
        there, the boundary at which a real eviction lands.
        """
        import time as _time

        batches = 0
        try:
            while True:
                if self.guard is not None and self.guard.triggered:
                    self._evict()
                    break
                if max_batches is not None and batches >= max_batches:
                    break
                if before_batch is not None:
                    before_batch(self.stream_step)
                    if self.guard is not None and self.guard.triggered:
                        self._evict()
                        break
                got = self.step()
                if got is None:
                    if not follow:
                        break
                    _time.sleep(self.stream.poll_wait_s)
                    continue
                batches += 1
        finally:
            # Same exit contract as the training loop: only committed
            # steps are left behind for the next reader.
            drain_checkpoints(self.manager)
        return self.model()

    def _evict(self) -> None:
        """Eviction: the last commit already carries the cursor — drain
        the writer so it is durably on disk, then return resumable."""
        drain_checkpoints(self.manager)
        record_event("signal", "stream_evicted", step=self.stream_step,
                     signal=self.guard.signal_name)
        dump_flight("stream_eviction")
        self.metrics.gauge("preempted", 1)
        self.metrics.note(
            "preempted",
            f"{self.guard.signal_name} at stream step {self.stream_step}; "
            "offset cursor committed and drained — re-run to resume",
        )

    # -- warm retrain --------------------------------------------------------

    def retrain(self, num_iterations: int | None = None) -> None:
        """Warm full retrain on the merged state, current factors as seed.

        Rebuilds the dataset from base + every committed upsert and runs
        the resilient stepped training loop (``train_als(warm_start=...)``)
        — the movie side finally sees the streamed ratings.  The retrained
        factors are permuted back into the session's row order (streamed-in
        users keep their appended rows, so crash replay still lines up)
        and committed with the unchanged cursor.
        """
        import dataclasses as _dc

        from cfk_tpu.data.blocks import Dataset
        from cfk_tpu.models.als import train_als

        if self._offload:
            raise NotImplementedError(
                "warm full retrain in an offload_tier='host_window' "
                "session needs warm_start threading through the windowed "
                "trainer (documented follow-up) — run the retrain "
                "offline and bootstrap a fresh session from its model"
            )
        with self.metrics.phase("retrain_build"):
            coo = self.state.to_coo()
            ds2 = Dataset.from_coo(
                coo,
                num_shards=1,
                pad_multiple=self.config.pad_multiple,
                layout=self.config.layout,
                chunk_elems=self.config.chunk_cells(),
                dense_stream=self.config.layout == "tiled",
            )
        if not np.array_equal(ds2.movie_map.raw_ids,
                              self.dataset.movie_map.raw_ids):
            raise RuntimeError(
                "merged state changed the movie universe — unknown movies "
                "are supposed to be rejected at apply time"
            )
        raw_users = self.state.user_raw_ids()
        perm = ds2.user_map.to_dense(raw_users)  # ds2 row per session row
        # Seed ds2's row order from the live factors.
        k = self.config.rank
        u_seed = np.zeros((ds2.user_blocks.padded_entities, k),
                          dtype=self._u.dtype)
        u_seed[perm] = self._u[: self.state.num_users]
        m_seed = np.asarray(self._m)[: ds2.movie_blocks.padded_entities]
        if m_seed.shape[0] < ds2.movie_blocks.padded_entities:
            m_seed = np.concatenate([
                m_seed,
                np.zeros((ds2.movie_blocks.padded_entities - m_seed.shape[0],
                          k), m_seed.dtype),
            ])
        cfg = self.config
        if num_iterations is not None:
            cfg = _dc.replace(cfg, num_iterations=num_iterations)
        with self.metrics.phase("retrain"):
            model = train_als(
                ds2, cfg, metrics=self.metrics,
                warm_start=(u_seed, m_seed),
                preemption_guard=self.guard,
            )
        # Back into session row order; new users keep their appended rows.
        u2 = np.asarray(model.user_factors)
        u_sess = np.zeros_like(self._u)
        u_sess[: self.state.num_users] = u2[perm]
        self._u = u_sess
        self._set_movie(model.movie_factors)
        self.metrics.incr("stream_retrains")
        self._commit(note=f"warm retrain at step {self.stream_step}")
        self._fire_commit({
            "retrain": True,
            "user_factors": np.array(self._u, np.float32),
            "movie_factors": np.array(np.asarray(self._m), np.float32),
        })
