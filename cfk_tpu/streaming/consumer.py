"""Offset-cursor consumer: exactly-once micro-batch assembly from the log.

At-least-once transports deliver duplicated, reordered, and dropped
records (the failure modes the reference's README recounts — its EOF race,
its hang on a lost message).  ``StreamConsumer`` turns that into an
exactly-once batch contract the fold-in math can rely on:

- A micro-batch is a CONTIGUOUS log-offset range ``[cursor, target)`` per
  partition, where ``target = min(end_offset, cursor + batch_records)``.
  The batch's content is a pure function of the durable log — never of
  delivery behavior.
- Duplicated delivery is dropped by offset (first copy wins; a conflicting
  second copy at the same offset is corruption and raises), reordered
  delivery is healed by the offset sort, and a gap (dropped delivery) is
  re-polled until the range is complete — bounded by ``gap_retries``, then
  a loud ``StreamGapError`` naming the missing offsets instead of the
  reference's forever-hang.

Because batch boundaries are offsets, a crash replay from a committed
cursor re-assembles bit-identical batches, and since the fold-in solve is
deterministic per batch, recovered factors are bit-identical to an
uninterrupted run (``tests/test_streaming.py``,
``scripts/chaos_lab.py --scenario stream_crash_replay``).
"""

from __future__ import annotations

import dataclasses
import time

from cfk_tpu.streaming.producer import UPDATES_TOPIC
from cfk_tpu.transport.broker import Transport
from cfk_tpu.transport.serdes import RatingUpdate, decode_rating_update


class StreamGapError(RuntimeError):
    """A batch's offset range stayed incomplete past the re-poll budget."""


@dataclasses.dataclass(frozen=True)
class StreamBatch:
    """One assembled micro-batch: updates in canonical (partition, offset)
    order plus the cursor movement its commit must persist."""

    updates: tuple[RatingUpdate, ...]
    cursors_before: dict[int, int]
    cursors_after: dict[int, int]
    duplicates_dropped: int = 0
    gap_repolls: int = 0

    @property
    def num_records(self) -> int:
        return sum(
            self.cursors_after[p] - self.cursors_before[p]
            for p in self.cursors_after
        )


class StreamConsumer:
    """Assemble exactly-once micro-batches from the updates topic."""

    def __init__(
        self,
        transport: Transport,
        *,
        topic: str = UPDATES_TOPIC,
        cursors: dict[int, int] | None = None,
        gap_retries: int = 20,
        gap_wait_s: float = 0.05,
    ) -> None:
        self.transport = transport
        self.topic = topic
        self.num_partitions = transport.num_partitions(topic)
        self.cursors = {p: 0 for p in range(self.num_partitions)}
        if cursors:
            for p, off in cursors.items():
                p = int(p)
                if p not in self.cursors:
                    raise ValueError(
                        f"cursor for partition {p} but topic {topic!r} has "
                        f"{self.num_partitions} partitions — was the topic "
                        "re-partitioned under a live cursor?"
                    )
                self.cursors[p] = int(off)
        self.gap_retries = gap_retries
        self.gap_wait_s = gap_wait_s

    def backlog(self) -> int:
        """Records appended but not yet consumed (across partitions)."""
        return sum(
            max(0, self.transport.end_offset(self.topic, p) - self.cursors[p])
            for p in range(self.num_partitions)
        )

    def _collect_range(self, p: int, lo: int, hi: int):
        """All records of partition ``p`` with offsets exactly [lo, hi) —
        deduped by offset, sorted, gaps re-polled (at-least-once healing)."""
        seen: dict[int, bytes] = {}
        dups = 0
        repolls = 0
        attempts = 0
        while True:
            this_pass: set[int] = set()
            for rec in self.transport.consume(self.topic, p, start_offset=lo):
                if rec.offset >= hi:
                    # Transports re-deliver from a *position*, so anything
                    # past the target belongs to the next batch.  Once the
                    # range is complete, the first past-target record ends
                    # the pass (reading on to the log's END would make
                    # every poll O(log tail) and a full drain quadratic in
                    # log length) — but only then, so an in-range duplicate
                    # delivered at the range's tail is still seen and
                    # counted before the break.
                    if len(seen) == hi - lo:
                        break
                    continue
                if rec.offset < lo:
                    continue
                prev = seen.get(rec.offset)
                if prev is None:
                    seen[rec.offset] = rec.value
                    this_pass.add(rec.offset)
                elif prev != rec.value:
                    raise StreamGapError(
                        f"partition {p} offset {rec.offset}: two deliveries "
                        "with different payloads — the log is corrupt, not "
                        "merely duplicated"
                    )
                elif rec.offset in this_pass:
                    # Only a second copy within ONE delivery pass is a
                    # transport duplicate; re-seeing offsets on a gap
                    # re-poll is our own doing and must not inflate the
                    # duplicate counter (it would misattribute a drop
                    # fault as a duplication fault).
                    dups += 1
            missing = [o for o in range(lo, hi) if o not in seen]
            if not missing:
                return [seen[o] for o in range(lo, hi)], dups, repolls
            attempts += 1
            if attempts > self.gap_retries:
                raise StreamGapError(
                    f"partition {p}: offsets {missing[:8]}{'...' if len(missing) > 8 else ''} "
                    f"never delivered after {self.gap_retries} re-polls; the "
                    "log claims end_offset past them, so the transport is "
                    "dropping records persistently (the reference hangs "
                    "forever in this state — we fail loudly)"
                )
            repolls += 1
            time.sleep(self.gap_wait_s)

    def poll(self, batch_records: int) -> StreamBatch | None:
        """Assemble the next micro-batch, or None when fully caught up.

        ``batch_records`` bounds the records taken per PARTITION this poll
        (the batch boundary is offset-determined, so replays re-cut the
        same batches).  Updates are returned in (partition, offset) order —
        the canonical order the dedup/fold-in applies them in.
        """
        if batch_records < 1:
            raise ValueError(f"batch_records must be >= 1, got {batch_records}")
        before = dict(self.cursors)
        after = dict(self.cursors)
        updates: list[RatingUpdate] = []
        dups = 0
        repolls = 0
        for p in range(self.num_partitions):
            lo = self.cursors[p]
            hi = min(self.transport.end_offset(self.topic, p),
                     lo + batch_records)
            if hi <= lo:
                continue
            values, d, r = self._collect_range(p, lo, hi)
            dups += d
            repolls += r
            updates.extend(decode_rating_update(v) for v in values)
            after[p] = hi
        if after == before:
            return None
        self.cursors = after
        return StreamBatch(
            updates=tuple(updates),
            cursors_before=before,
            cursors_after=after,
            duplicates_dropped=dups,
            gap_repolls=repolls,
        )
