"""Deduplicated per-user rating state: the idempotency layer of fold-in.

The fold-in solve is stateless per user — it re-derives a touched user's
factor row from that user's COMPLETE current ratings against the fixed
movie factors — so applying the same logical update twice, or applying two
updates to the same cell in either order, must converge to the same state.
``StreamState`` provides exactly that: the merge of the base dataset's
ratings and every applied ``(user, movie, rating, seq)`` upsert, with
last-seq-wins per (user, movie) cell (equal seq = a retried append,
dropped).

Nothing here is persisted: the state is a deterministic function of (base
dataset, the updates-log prefix below the committed cursor), so crash
recovery rebuilds it by replaying the log — the factors + cursor commit
(``cfk_tpu.streaming.session``) is the only durable artifact.

Application is TRANSACTIONAL: ``stage()`` computes the post-batch view
without mutating anything, the session solves and probes against it, and
only a healthy solve ``commit()``s — a poisoned micro-batch is discarded
wholesale, leaving both the served factors and the state they were solved
from untouched.

Base ratings carry seq −1 (every streamed update outranks the batch file);
new users grow the user table in first-appearance order within the
canonical batch order, which makes row assignment replay-deterministic.
Updates naming a movie the model has never seen have no factor column to
solve against — they are counted and dropped (``unknown_movie``), to be
picked up when the operator retrains from base + log.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cfk_tpu.transport.serdes import RatingUpdate

_BASE_SEQ = -1


@dataclasses.dataclass
class ApplyStats:
    """What one batch application did — chaos tests assert these fired."""

    fresh: int = 0          # state-changing upserts applied
    stale: int = 0          # outranked by an already-applied seq (dup/reorder)
    unknown_movie: int = 0  # no factor column for this movie — dropped
    new_users: int = 0      # rows grown for first-seen users


@dataclasses.dataclass(frozen=True)
class PendingApply:
    """A staged (not yet committed) batch application."""

    touched_rows: tuple[int, ...]          # sorted dense user rows to re-solve
    new_user_raw: tuple[int, ...]          # raw ids of rows grown, in order
    cell_writes: dict                      # row -> {movie_row: (rating, seq)}
    stats: ApplyStats


class StreamState:
    """Merged base + streamed rating state, queryable per user row."""

    def __init__(self, dataset) -> None:
        coo = dataset.coo_dense  # dense-index COO
        self._movie_raw = dataset.movie_map.raw_ids
        self.num_movies = dataset.movie_map.num_entities
        self._base_user_raw = dataset.user_map.raw_ids
        # Per-user CSR over the base ratings (built once, never mutated):
        # streamed deltas overlay it per touched user.
        order = np.argsort(coo.user_raw, kind="stable")
        self._base_movies = coo.movie_raw[order].astype(np.int32)
        self._base_ratings = coo.rating[order].astype(np.float32)
        counts = np.bincount(
            coo.user_raw.astype(np.int64),
            minlength=dataset.user_map.num_entities,
        )
        self._base_indptr = np.zeros(
            dataset.user_map.num_entities + 1, np.int64
        )
        np.cumsum(counts, out=self._base_indptr[1:])
        # Streamed overlay: row -> {movie_row: (rating, seq)}; rows past the
        # base user count are streamed-in new users.
        self._delta: dict[int, dict[int, tuple[float, int]]] = {}
        self._new_user_raw: list[int] = []
        self._new_user_rows: dict[int, int] = {}
        self.applied_seq_high = _BASE_SEQ

    # -- identity ------------------------------------------------------------

    @property
    def num_base_users(self) -> int:
        return int(self._base_user_raw.shape[0])

    @property
    def num_users(self) -> int:
        return self.num_base_users + len(self._new_user_raw)

    def user_row(self, raw: int) -> int | None:
        """Dense row of a raw user id, or None if never seen."""
        got = self._new_user_rows.get(int(raw))
        if got is not None:
            return got
        i = int(np.searchsorted(self._base_user_raw, raw))
        if i < self.num_base_users and int(self._base_user_raw[i]) == int(raw):
            return i
        return None

    def user_raw_ids(self) -> np.ndarray:
        """Raw ids in row order (base ascending, then streamed new users)."""
        return np.concatenate([
            self._base_user_raw,
            np.asarray(self._new_user_raw, np.int64),
        ]) if self._new_user_raw else self._base_user_raw

    def movie_row(self, raw: int) -> int | None:
        i = int(np.searchsorted(self._movie_raw, raw))
        if i < self.num_movies and int(self._movie_raw[i]) == int(raw):
            return i
        return None

    # -- queries -------------------------------------------------------------

    def _cells(self, row: int, overlay: dict | None = None
               ) -> dict[int, tuple[float, int]]:
        """row's full (movie_row -> (rating, seq)) map, base + delta
        (+ an optional staged overlay for that row)."""
        cells: dict[int, tuple[float, int]] = {}
        if row < self.num_base_users:
            lo, hi = self._base_indptr[row], self._base_indptr[row + 1]
            for mv, rt in zip(self._base_movies[lo:hi],
                              self._base_ratings[lo:hi]):
                cells[int(mv)] = (float(rt), _BASE_SEQ)
        cells.update(self._delta.get(row, {}))
        if overlay:
            cells.update(overlay)
        return cells

    def neighbors(self, row: int, overlay: dict | None = None
                  ) -> tuple[np.ndarray, np.ndarray]:
        """(movie rows int32 ascending, ratings f32) for one user row.

        Sorted by movie row — the canonical neighbor order, so the solve
        input (and therefore its bits) depends only on the state, never on
        arrival order.
        """
        cells = self._cells(row, overlay)
        if not cells:
            return (np.zeros(0, np.int32), np.zeros(0, np.float32))
        movies = np.fromiter(cells.keys(), np.int32, len(cells))
        ratings = np.fromiter(
            (cells[int(m)][0] for m in movies), np.float32, len(cells)
        )
        order = np.argsort(movies, kind="stable")
        return movies[order], ratings[order]

    def to_coo(self):
        """The merged rating state as a raw-id COO (for warm full retrains:
        base + every committed upsert, exactly what the factors model).

        Rows the stream never touched pass through vectorized (deduped to
        last-occurrence per cell, matching ``_cells``'s dict semantics for
        repeated base observations); only delta rows pay the per-row merge
        — O(touched) Python work, not O(all users), so ML-25M-scale exits
        and periodic retrains don't stall on an interpreter loop."""
        from cfk_tpu.data.blocks import RatingsCOO

        raw_users = self.user_raw_ids()
        counts = np.diff(self._base_indptr)
        base_rows = np.repeat(
            np.arange(self.num_base_users, dtype=np.int64), counts
        )
        # last-occurrence dedup per (row, movie) cell: stable sort keeps
        # original order within equal keys, so each group's tail is the
        # entry _cells would have kept
        key = base_rows * np.int64(self.num_movies) + self._base_movies
        order = np.argsort(key, kind="stable")
        ks = key[order]
        last = np.ones(ks.shape[0], bool)
        last[:-1] = ks[1:] != ks[:-1]
        sel = order[last]
        untouched = ~np.isin(base_rows[sel],
                             np.fromiter(self._delta, np.int64,
                                         len(self._delta)))
        sel = sel[untouched]
        users = [self._base_user_raw[base_rows[sel]]]
        movies = [self._movie_raw[self._base_movies[sel]].astype(np.int64)]
        ratings = [self._base_ratings[sel]]
        for row in sorted(self._delta):
            mv, rt = self.neighbors(row)
            users.append(np.full(mv.shape[0], raw_users[row], np.int64))
            movies.append(self._movie_raw[mv].astype(np.int64))
            ratings.append(rt)
        return RatingsCOO(
            movie_raw=np.concatenate(movies),
            user_raw=np.concatenate(users),
            rating=np.concatenate(ratings).astype(np.float32),
        )

    # -- transactional application -------------------------------------------

    def stage(self, updates: tuple[RatingUpdate, ...] | list[RatingUpdate]
              ) -> PendingApply:
        """Dedup a batch against the applied state WITHOUT mutating it.

        Updates must already be in canonical order (the consumer's
        (partition, offset) order).  Within the batch the same cell may be
        written repeatedly — the highest seq wins; against the applied
        state, only upserts whose seq outranks the cell's current seq are
        fresh.  A user whose batch records are ALL stale is not touched
        (no re-solve — the idempotent no-op for retried appends).
        """
        stats = ApplyStats()
        writes: dict[int, dict[int, tuple[float, int]]] = {}
        cells_cache: dict[int, dict] = {}  # applied view, one build per row
        new_raw: list[int] = []
        new_rows: dict[int, int] = {}
        next_row = self.num_users
        for upd in updates:
            mv = self.movie_row(upd.movie)
            if mv is None:
                stats.unknown_movie += 1
                continue
            row = self.user_row(upd.user)
            if row is None:
                row = new_rows.get(int(upd.user))
            if row is None:
                row = next_row
                new_rows[int(upd.user)] = row
                new_raw.append(int(upd.user))
                next_row += 1
                stats.new_users += 1
            current = writes.get(row, {}).get(mv)
            if current is None:
                cells = cells_cache.get(row)
                if cells is None:
                    cells = cells_cache[row] = (
                        self._cells(row) if row < self.num_users else {}
                    )
                current = cells.get(mv)
            if current is not None and upd.seq <= current[1]:
                stats.stale += 1
                continue
            writes.setdefault(row, {})[mv] = (float(upd.rating), int(upd.seq))
            stats.fresh += 1
        return PendingApply(
            touched_rows=tuple(sorted(writes)),
            new_user_raw=tuple(new_raw),
            cell_writes=writes,
            stats=stats,
        )

    def commit(self, pending: PendingApply) -> None:
        """Fold a staged batch into the applied state."""
        for raw in pending.new_user_raw:
            self._new_user_rows[raw] = self.num_users
            self._new_user_raw.append(raw)
        for row, cells in pending.cell_writes.items():
            self._delta.setdefault(row, {}).update(cells)
            self.applied_seq_high = max(
                self.applied_seq_high, max(s for _, s in cells.values())
            )
