"""Incremental fold-in: solve ONLY the touched users against fixed movies.

Exactly one ALS half-iteration restricted to the touched rows — the math
the ROADMAP names: each touched user's normal equations

    (Σ m mᵀ + λ·n·I) u = Σ r·m        over that user's CURRENT ratings

solved against the fixed movie factors, so the existing chunked Gram+solve
machinery applies verbatim on a tiny entity set.  Two layouts:

- ``"padded"`` — one [T, P] rectangle built directly from the touched
  users' neighbor lists and solved by ``ops.solve.als_half_step`` (the
  single-rectangle reference path; the default for micro-batches, whose
  rectangles are tiny).
- ``"tiled"`` — ``data.blocks.build_tiled_blocks`` over the touched set,
  solved by ``ops.tiled.tiled_half_step`` — the same kernels the at-scale
  trainer runs, fused Gram+solve epilogue and in-kernel gather included
  (they engage under the identical gates; on CPU CI both route through
  their bit-exact XLA emulation twins).

Shapes are bucketed to powers of two (entity count and rectangle width) so
a long-running stream converges onto a handful of compiled programs
instead of re-tracing every batch.

Determinism contract: the solved rows are a deterministic function of
(neighbor lists, movie factors, solve configuration) — neighbor lists
arrive sorted by movie row (``StreamState.neighbors``), so the same batch
always produces bit-identical rows.  Rows ARE sensitive at the last-ulp
level to the batch's composition (co-members set the padded width and the
batch GEMM shapes), which is why the exactly-once pipeline pins batch
boundaries to log offsets: replayed and fault-injected deliveries re-cut
bit-identical batches (``cfk_tpu.streaming.consumer``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cfk_tpu.ops.solve import als_half_step
from cfk_tpu.ops.tiled import tiled_half_step


def _pow2_ceil(x: int, floor: int) -> int:
    out = floor
    while out < x:
        out *= 2
    return out


# Trace counter (ISSUE 13): bumped once per TRACE of a fold-in program
# (the bodies run only while jax traces a new shape bucket), so the
# session's prewarm() can pin its zero-new-traces contract and the bench
# fold-in row can report trace_count alongside updates/s.
_TRACES = [0]


def trace_count() -> int:
    """Fold-in program traces this process (both layouts)."""
    return _TRACES[0]


@functools.partial(
    jax.jit,
    static_argnames=("lam", "solver", "reg_solve_algo"),
)
def _padded_fold(fixed, neighbor_idx, rating, mask, count, *, lam, solver,
                 reg_solve_algo):
    _TRACES[0] += 1
    return als_half_step(
        fixed, neighbor_idx, rating, mask, count, lam,
        solver=solver, reg_solve_algo=reg_solve_algo,
    )


@functools.partial(
    jax.jit,
    static_argnames=("chunks", "entities", "lam", "solver", "fused_epilogue",
                     "in_kernel_gather", "reg_solve_algo"),
)
def _tiled_fold(fixed, blk, *, chunks, entities, lam, solver, fused_epilogue,
                in_kernel_gather, reg_solve_algo):
    _TRACES[0] += 1
    return tiled_half_step(
        fixed, blk, chunks, entities, lam, solver=solver,
        fused_epilogue=fused_epilogue, in_kernel_gather=in_kernel_gather,
        reg_solve_algo=reg_solve_algo,
    )


def fold_in_rows(
    movie_factors,
    neighbor_data,
    *,
    lam: float,
    solver: str = "auto",
    layout: str = "padded",
    pad_multiple: int = 8,
    fused_epilogue: bool | None = None,
    in_kernel_gather: bool | None = None,
    reg_solve_algo: str | None = None,
) -> np.ndarray:
    """Solve the touched users' rows against fixed ``movie_factors``.

    ``neighbor_data`` is a sequence of ``(movie_rows int32, ratings f32)``
    pairs, one per touched user, each sorted by movie row.  Returns the
    solved float32 rows ``[len(neighbor_data), k]`` in the same order.
    """
    t = len(neighbor_data)
    if t == 0:
        return np.zeros((0, movie_factors.shape[-1]), np.float32)
    if layout == "tiled":
        return _fold_tiled(
            movie_factors, neighbor_data, lam=lam, solver=solver,
            fused_epilogue=fused_epilogue, in_kernel_gather=in_kernel_gather,
            reg_solve_algo=reg_solve_algo,
        )
    if layout != "padded":
        raise ValueError(
            f"fold-in layout must be 'padded' or 'tiled', got {layout!r}"
        )
    width = max(int(mv.shape[0]) for mv, _ in neighbor_data)
    p = _pow2_ceil(max(width, 1), max(pad_multiple, 1))
    e = _pow2_ceil(t, 8)
    neighbor_idx = np.zeros((e, p), np.int32)
    rating = np.zeros((e, p), np.float32)
    mask = np.zeros((e, p), np.float32)
    count = np.zeros((e,), np.float32)
    for i, (mv, rt) in enumerate(neighbor_data):
        n = mv.shape[0]
        neighbor_idx[i, :n] = mv
        rating[i, :n] = rt
        mask[i, :n] = 1.0
        count[i] = n
    out = _padded_fold(
        movie_factors, jnp.asarray(neighbor_idx), jnp.asarray(rating),
        jnp.asarray(mask), jnp.asarray(count),
        lam=float(lam), solver=solver, reg_solve_algo=reg_solve_algo,
    )
    return np.asarray(out[:t], np.float32)


def fold_in_rows_windowed(
    movie_store,
    neighbor_data,
    *,
    lam: float,
    solver: str = "auto",
    pad_multiple: int = 8,
    reg_solve_algo: str | None = None,
    stats: dict | None = None,
    return_staged: bool = False,
):
    """Restricted fold-in against an OUT-OF-CORE movie table (ISSUE 19).

    ``movie_store`` is a host-resident ``offload.store.HostFactorStore``;
    the batch's touched movie rows stage as ONE ad-hoc window (unique
    referenced rows gathered host-side, one ``device_put``), neighbor
    indices rebase into the window via ``searchsorted``, and the SAME
    ``_padded_fold`` program solves the identical pow2 rectangle — so the
    solved rows are BIT-IDENTICAL to ``fold_in_rows`` over the full
    device-resident table (the gather reads the same values; mask-0 cells
    contribute exact zeros; the rectangle shape is unchanged, so the
    batched solve bits are too).  The window row count buckets to pow2
    (min 8) so a long-running stream converges onto the same handful of
    compiled programs the resident path enjoys; pad slots replicate
    window row 0 (masked out — exact zero contribution).

    ``return_staged=True`` additionally returns the staged window (the
    device array the solve read), so the caller's health probe can run
    against the rows actually consumed — the out-of-core twin of probing
    the resident table.  ``stats`` (a dict) receives
    ``foldin_windows_staged`` / ``foldin_staged_bytes`` increments.
    """
    t = len(neighbor_data)
    k = movie_store.rank
    if t == 0:
        empty = np.zeros((0, k), np.float32)
        return (empty, None) if return_staged else empty
    touched = (np.unique(np.concatenate(
        [mv.astype(np.int64) for mv, _ in neighbor_data]))
        if any(mv.shape[0] for mv, _ in neighbor_data)
        else np.zeros((1,), np.int64))
    if touched.size == 0:
        touched = np.zeros((1,), np.int64)
    w = _pow2_ceil(int(touched.size), 8)
    rows = np.concatenate([
        touched, np.full(w - touched.size, touched[0], np.int64)
    ])
    window = movie_store.gather(rows)
    if stats is not None:
        stats["foldin_windows_staged"] = (
            stats.get("foldin_windows_staged", 0) + 1)
        stats["foldin_staged_bytes"] = (
            stats.get("foldin_staged_bytes", 0) + window.nbytes)
    staged = jnp.asarray(window)
    width = max(int(mv.shape[0]) for mv, _ in neighbor_data)
    p = _pow2_ceil(max(width, 1), max(pad_multiple, 1))
    e = _pow2_ceil(t, 8)
    neighbor_idx = np.zeros((e, p), np.int32)
    rating = np.zeros((e, p), np.float32)
    mask = np.zeros((e, p), np.float32)
    count = np.zeros((e,), np.float32)
    for i, (mv, rt) in enumerate(neighbor_data):
        n = mv.shape[0]
        neighbor_idx[i, :n] = np.searchsorted(
            touched, mv.astype(np.int64)).astype(np.int32)
        rating[i, :n] = rt
        mask[i, :n] = 1.0
        count[i] = n
    out = _padded_fold(
        staged, jnp.asarray(neighbor_idx), jnp.asarray(rating),
        jnp.asarray(mask), jnp.asarray(count),
        lam=float(lam), solver=solver, reg_solve_algo=reg_solve_algo,
    )
    solved = np.asarray(out[:t], np.float32)
    return (solved, staged) if return_staged else solved


def _fold_tiled(movie_factors, neighbor_data, *, lam, solver, fused_epilogue,
                in_kernel_gather, reg_solve_algo):
    from cfk_tpu.data.blocks import build_tiled_blocks
    from cfk_tpu.models.als import _tiled_to_device

    t = len(neighbor_data)
    solve_dense = np.concatenate([
        np.full(mv.shape[0], i, np.int64)
        for i, (mv, _) in enumerate(neighbor_data)
    ])
    fixed_dense = np.concatenate(
        [mv.astype(np.int64) for mv, _ in neighbor_data]
    )
    rating = np.concatenate([rt for _, rt in neighbor_data])
    blocks = build_tiled_blocks(
        solve_dense, fixed_dense, rating, t,
        int(movie_factors.shape[0]),
    )
    blk = _tiled_to_device(blocks)
    out = _tiled_fold(
        movie_factors, blk,
        chunks=("tiled", blocks.mode) + blocks.statics,
        entities=blocks.padded_entities,
        lam=float(lam), solver=solver, fused_epilogue=fused_epilogue,
        in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
    )
    return np.asarray(out[:t], np.float32)
