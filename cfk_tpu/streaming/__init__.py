"""Exactly-once streaming fold-in: the rate → fold-in → resume loop.

The durable updates topic (``producer``), the offset-cursor consumer with
exactly-once micro-batch assembly (``consumer``), the idempotent
deduplicated rating state (``state``), the restricted-half-iteration solve
(``foldin``), and the session that ties them to the resilience stack and
commits factors atomically with the cursor (``session``).  See
ARCHITECTURE.md "Streaming ingest & incremental fold-in".
"""

from cfk_tpu.streaming.consumer import (
    StreamBatch,
    StreamConsumer,
    StreamGapError,
)
from cfk_tpu.streaming.foldin import fold_in_rows
from cfk_tpu.streaming.producer import (
    UPDATES_TOPIC,
    StreamProducer,
    ensure_updates_topic,
)
from cfk_tpu.streaming.session import (
    PoisonedBatchError,
    StreamConfig,
    StreamSession,
)
from cfk_tpu.streaming.state import ApplyStats, PendingApply, StreamState

__all__ = [
    "ApplyStats",
    "PendingApply",
    "PoisonedBatchError",
    "StreamBatch",
    "StreamConfig",
    "StreamConsumer",
    "StreamGapError",
    "StreamProducer",
    "StreamSession",
    "StreamState",
    "UPDATES_TOPIC",
    "ensure_updates_topic",
    "fold_in_rows",
]
