import sys

from cfk_tpu.cli import main

sys.exit(main())
