"""The memory-budget predicate shared by the planner and the executor.

ISSUE 11's contract: a plan can never promise a resident factor table that
does not fit — so the predicate that decides whether the ``device`` tier is
feasible (``cfk_tpu.plan.resolver``) must be the SAME arithmetic the
offload executor sizes its windows with (``cfk_tpu.offload.windowed``).
Both import it from here.  Deliberately importable without jax (like
``config.py`` / ``plan/spec.py``): the plan CLI prices billion-interaction
shapes on machines that could never hold them.

What counts as resident for one training iteration (the ``device`` tier):

- both factor tables at the storage dtype (master + the solve-side output
  alive concurrently — the half-steps read one side while writing the
  other, and the gather paths keep a zero-row-appended working copy of the
  fixed side, charged as one extra fixed-table copy at the table dtype);
- the block arrays: per rating per side, neighbor index + rating +
  weight/meta (int32/f32 each), inflated by the tiled layout's measured
  tile-padding share;
- the transient chunk working set is bounded by ``chunk_elems`` and small
  next to the above — it rides the headroom fraction.

``RESIDENT_FRACTION`` leaves headroom for accumulators, carries, and the
runtime; the same fraction gates planning and execution so they cannot
disagree at the boundary.
"""

from __future__ import annotations

RESIDENT_FRACTION = 0.9
# Staged window double-buffer: two windows (current + prefetched) are alive
# at once, so the per-window budget is half the staging share.
WINDOW_BUFFERS = 2
# Tiled stream padding share (the measured tile-padding factor at the full
# Netflix build — cfk_tpu/plan/cost.py's _GATHER_PAD_FACTOR["tiled"]).
_TILE_PAD = 1.26
# Bytes per rating per side in the stream blocks: neighbor idx (4) +
# rating (4) + weight (4).
_BLOCK_BYTES_PER_CELL = 12.0


def dtype_bytes(name: str | None) -> int:
    """Itemsize of a factor-storage / table dtype name (None → float32)."""
    return {None: 4, "float32": 4, "bfloat16": 2, "int8": 1}[name]


def factor_table_bytes(entities: int, rank: int,
                       dtype: str | None = "float32") -> float:
    return float(entities) * rank * dtype_bytes(dtype)


def shard_entity_range(rows: int, num_shards: int, shard: int
                       ) -> tuple[int, int]:
    """Entity-range shard ``shard``'s [lo, hi) rows — the SAME clipped
    ceil-split ``HostFactorStore`` places shards with (a ceil-split can
    overshoot ``rows`` by more than one shard: rows=10 / 7 shards walks
    past 10 at shard 5, so both bounds clip and trailing shards are
    empty)."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if not 0 <= shard < num_shards:
        raise ValueError(f"shard {shard} outside [0, {num_shards})")
    per = -(-rows // num_shards)
    return min(shard * per, rows), min((shard + 1) * per, rows)


def train_resident_bytes(num_users: int, num_movies: int, nnz: int,
                         rank: int, *, dtype: str = "float32",
                         table_dtype: str | None = None,
                         num_shards: int = 1,
                         donation: bool = True) -> dict:
    """PER-SHARD resident bytes of one device-tier training iteration.

    Returns the breakdown dict (the scale lab records it per row); the
    ``total`` key is what ``fits_device`` compares against ONE device's
    budget.  Sharding divides what actually shards — each device holds
    its slice of the factor tables and its slice of the block arrays —
    but NOT the gather working copy: the all_gather exchange materializes
    the full fixed side on every device each half-iteration, which is
    exactly why an oversized table stays oversized at any shard count and
    the host_window tier remains the answer (the ring exchanges trade the
    copy for an [E_local, k, k] accumulator, bounded separately by the
    block builder's ``accum_max_entities`` gate).

    ``donation`` (ISSUE 13): the resident trainers donate their factor
    arguments through the iteration jit (``models/als.py`` /
    ``parallel/spmd.py`` ``donate_argnums=(0, 1)``), so a half-step's
    solved output ALIASES the side it replaces — input and output never
    coexist, which is the arithmetic the default charges (bit-identical
    to the pre-ISSUE-13 totals).  ``donation=False`` is the un-donated
    accounting: the larger side's fresh output buffer coexists with its
    predecessor at the solve boundary, charged as one extra table side —
    the credit the scale-sweep rows record so a tier decision that only
    holds BECAUSE of donation is visible in provenance."""
    shards = max(int(num_shards), 1)
    tables = factor_table_bytes(num_users + num_movies, rank, dtype) / shards
    # The gather working copy of the fixed side (zero-row append / quantized
    # view); charge the LARGER side at the effective gather cell size.
    gather_copy = factor_table_bytes(
        max(num_users, num_movies), rank,
        table_dtype if table_dtype is not None else dtype,
    )
    blocks = 2.0 * nnz * _BLOCK_BYTES_PER_CELL * _TILE_PAD / shards
    solve_output = (
        0.0 if donation
        else factor_table_bytes(max(num_users, num_movies), rank, dtype)
        / shards
    )
    total = tables + gather_copy + blocks + solve_output
    return {
        "factor_tables_bytes": tables,
        "gather_copy_bytes": gather_copy,
        "block_arrays_bytes": blocks,
        "solve_output_bytes": solve_output,
        "num_shards": shards,
        "total": total,
    }


def fits_device(num_users: int, num_movies: int, nnz: int, rank: int, *,
                hbm_bytes: float, dtype: str = "float32",
                table_dtype: str | None = None,
                num_shards: int = 1, donation: bool = True) -> bool:
    """THE device-tier feasibility predicate (planner AND executor) —
    per-shard arithmetic against ONE device's budget.  ``donation=True``
    (the default — the trainers really do donate) credits the solved
    side's aliased output; False is the un-donated comparison arm."""
    return (
        train_resident_bytes(
            num_users, num_movies, nnz, rank,
            dtype=dtype, table_dtype=table_dtype, num_shards=num_shards,
            donation=donation,
        )["total"]
        <= hbm_bytes * RESIDENT_FRACTION
    )


def shape_fits_device(shape, device, table_dtype: str | None = None,
                      donation: bool = True) -> bool:
    """``fits_device`` over a ``plan.ProblemShape`` + ``plan.DeviceSpec``
    (serve shapes are table-resident by construction and not gated here).
    ``table_dtype`` is the resolve's PINNED gather-table dtype when one
    exists — quantization shrinks the gather working copy, which is
    exactly the memory lever, so the predicate must charge it.  The
    shape's shard count divides the table/block terms (per-shard
    arithmetic; the gather copy replicates)."""
    if getattr(shape, "kind", "train") != "train":
        return True
    return fits_device(
        shape.num_users, shape.num_movies, shape.nnz, shape.rank,
        hbm_bytes=device.hbm_bytes, dtype=shape.dtype,
        table_dtype=table_dtype,
        num_shards=getattr(shape, "num_shards", 1), donation=donation,
    )


def window_budget_bytes(hbm_bytes: float,
                        reserved_bytes: float = 0.0,
                        buffers: int = WINDOW_BUFFERS) -> float:
    """Per-window staging budget: the headroom fraction of the device
    MINUS any persistent device state the driver holds alongside the
    windows (the ring modes' per-entity Gram accumulator — see
    ``ring_accumulator_reservation``), split across the ``buffers`` live
    windows.  ``buffers`` defaults to the classic double buffer (current
    + one prefetched); the pooled staging engine sizes its windows at the
    same 2 and then admits extra pool depth from the leftover share
    (``max_pool_depth``) — the "staging arena" term of ISSUE 13."""
    return max(
        hbm_bytes * RESIDENT_FRACTION - reserved_bytes, 0.0
    ) / max(int(buffers), 1)


def max_pool_depth(hbm_bytes: float, worst_window_bytes: float,
                   reserved_bytes: float = 0.0) -> int:
    """The deepest staging pool the budget admits: ``depth + 1`` windows
    (``depth`` staged ahead + one consuming) of the worst window must fit
    the staging share next to the reserved device state.  Never below 1
    (one window ahead == the classic double buffer's footprint)."""
    share = max(hbm_bytes * RESIDENT_FRACTION - reserved_bytes, 0.0)
    live = int(share // max(float(worst_window_bytes), 1.0))
    return max(live - 1, 1)


# --- hot-row device cache (ISSUE 15) ---------------------------------------
#
# The skew-aware hot partition keeps the top-f fixed-table rows device-
# resident at the STAGING dtype, so windows stage only their cold
# residual.  Its bytes are a RESERVATION next to the ring-accumulator
# term: persistent device state the window double-buffer split must not
# promise away.  The planner (plan/resolver.py) and the executor
# (offload/windowed.py) consult the SAME arithmetic here — the planner
# with the fraction cap below (it sizes no windows), the executor with
# the exact residual after the accumulator + window + delta-arena terms.

# The planner-side cap: the hot partition may claim at most this share of
# the budget fraction — the remainder is the window double buffer +
# accumulator share the resolver cannot size without the real blocks.
# The executor's exact arithmetic usually admits more; this cap only has
# to guarantee the resolver never promises a reservation the window
# sizing cannot live beside.
HOT_BUDGET_FRACTION = 0.5

# The planner's hot-fraction TARGET when the knob is free: on power-law
# data the top ~10% of rows covers well over half the references
# (data/synth.py's Zipf head; Netflix/ML-25M in the wild), so the
# resolver aims there and the executor clamps to the REAL coverage-curve
# knee of the plans' own row sets at build time.
HOT_ROW_TARGET_FRACTION = 0.10


def planner_hot_rows(num_users: int, num_movies: int, rank: int,
                     stage_dtype: str | None, hbm_bytes: float) -> int:
    """The resolver's hot-row target for a free ``hot_rows`` field: the
    ~10% power-law head, clamped by what the planner-side budget
    predicate admits (0 when the headroom refuses — the "nonzero only
    when the reservation fits" acceptance rule)."""
    target = int((num_users + num_movies) * HOT_ROW_TARGET_FRACTION)
    return min(target, max_hot_rows(hbm_bytes, rank, stage_dtype))


def stage_row_bytes(rank: int, stage_dtype: str | None) -> float:
    """Bytes one staged/hot-resident table row occupies at the staging
    dtype: ``rank`` cells plus the int8 scheme's per-row f32 scale."""
    cell = dtype_bytes(stage_dtype)
    overhead = 4.0 if stage_dtype == "int8" else 0.0
    return float(rank) * cell + overhead


def hot_reservation_bytes(hot_rows: int, rank: int,
                          stage_dtype: str | None) -> float:
    """Persistent device bytes of a ``hot_rows``-row hot partition (both
    sides' partitions sum — callers pass the total row count)."""
    return max(int(hot_rows), 0) * stage_row_bytes(rank, stage_dtype)


def delta_arena_bytes(window_rows: int, rank: int,
                      stage_dtype: str | None) -> float:
    """The delta-staging arena bound: ONE predecessor window's assembled
    table stays device-resident while its successor assembles (the
    device-to-device reuse source), on top of the classic double buffer —
    charged at the worst window's table share."""
    return float(window_rows) * stage_row_bytes(rank, stage_dtype)


def max_hot_rows(hbm_bytes: float, rank: int, stage_dtype: str | None,
                 reserved_bytes: float = 0.0) -> int:
    """The largest hot partition (total rows, both sides) the budget
    admits next to ``reserved_bytes`` of other persistent state.  The
    executor passes the exact reservation (ring accumulators + the live
    window buffers + the delta arena); the planner, which has not sized
    windows yet, passes 0 and the ``HOT_BUDGET_FRACTION`` cap holds the
    window share instead."""
    share = max(hbm_bytes * RESIDENT_FRACTION - reserved_bytes, 0.0)
    if reserved_bytes == 0.0:
        share *= HOT_BUDGET_FRACTION
    return int(share // stage_row_bytes(rank, stage_dtype))


def hot_reservation_fits(hot_rows: int, rank: int,
                         stage_dtype: str | None, hbm_bytes: float,
                         reserved_bytes: float = 0.0) -> bool:
    """THE hot-reservation predicate (planner AND executor): can a
    ``hot_rows``-row partition live beside ``reserved_bytes``?  The
    planner refuses a pinned-impossible reservation at resolution with
    this; the executor re-checks with its exact terms."""
    return (int(hot_rows)
            <= max_hot_rows(hbm_bytes, rank, stage_dtype,
                            reserved_bytes=reserved_bytes))


def ring_accumulator_bytes(local_entities: int, rank: int) -> float:
    """Persistent device bytes of one shard's ring-mode Gram accumulator:
    the f32 [E_local+1, k, k] + [E_local+1, k] carry pair the windowed
    ring driver holds across every window of a half-step (the same
    structure the resident ring carries in-place)."""
    return float(local_entities + 1) * rank * (rank + 1) * 4.0


def gram_accumulator_bytes(rank: int) -> float:
    """Persistent device bytes of the implicit path's global-Gram
    accumulator (ISSUE 19): the f32 [k, k] YᵀY of one fixed side, held
    across a half-step's windows and rebuilt per half."""
    return float(rank) * rank * 4.0


def gram_reservation_bytes(rank: int, stage_dtype: str | None, *,
                           block_rows: int = 4096) -> float:
    """What windowed iALS/iALS++ must RESERVE for the global-Gram
    reduction: the [k, k] f32 accumulator itself plus the double-buffered
    streamed factor blocks it is reduced from (``block_rows`` rows of the
    fixed store crossing PCIe at the staging dtype per reduction step —
    the same block grid the resident ``global_gram_blocked`` scans, so
    the windowed reduction is bit-identical to the resident Gram).

    The default ``block_rows`` mirrors ``ops.solve.GRAM_BLOCK_ROWS``; it
    is a parameter here because this module must import without jax."""
    return (gram_accumulator_bytes(rank)
            + WINDOW_BUFFERS * block_rows * stage_row_bytes(rank,
                                                            stage_dtype))


def ring_accumulator_reservation(local_entities: int, rank: int, *,
                                 donated: bool = True) -> float:
    """What the window sizing must RESERVE for the ring accumulator.

    With buffer donation through the per-window accumulation jit
    (``offload/windowed.py`` ``_ring_window_jit`` donates its carry pair,
    ISSUE 13 — the ``models/als.py``/``spmd.py`` idiom) the output
    accumulator ALIASES the input, so exactly one copy is live: ×1.
    Without donation the dispatch boundary keeps a window call's input
    AND output accumulators alive: ×2 — the PR 11 accounting, kept as
    the comparison arm so a shape that fits only because of donation is
    attributable to it."""
    return ((1.0 if donated else 2.0)
            * ring_accumulator_bytes(local_entities, rank))


def fleet_host_ram_bytes(num_users: int, num_movies: int, nnz: int,
                         rank: int, *, dtype: str = "float32",
                         processes: int = 1, armed: bool = True) -> dict:
    """PER-PROCESS host-RAM bytes of the FLEET out-of-core tier
    (ISSUE 17): what one host must hold when ``train_als_host_window``
    runs multi-process with per-process store slices and the distributed
    window exchange.

    - both factor-store SLICES at the storage dtype — the term that
      scales OUT with the fleet (the whole point: a table no single host
      fits splits across processes);
    - last-good snapshot copies of both slices when the sentinel is
      armed (the rollback ladder's in-RAM restore point — ×2 slices);
    - the residual mirror's worst case: every fixed-table row OUTSIDE
      the slice arrives over DCN for the larger side (value bytes + an
      int64 row id each).  The exchange manifests bound this exactly at
      plan time; this predicate prices shapes WITHOUT building plans, so
      it charges the all-remote-referenced ceiling;
    - this host's share of the block arrays (per-shard streams — the
      contiguous shard-block ownership splits them with the stores).

    Importable without jax, like the rest of this module — the plan CLI
    prices fleet shapes on a laptop."""
    p = max(int(processes), 1)
    row = rank * dtype_bytes(dtype)
    slices = float(num_users + num_movies) * row / p
    snapshots = slices if not armed else 2.0 * slices
    larger = float(max(num_users, num_movies))
    mirror = (larger - larger / p) * (row + 8.0)
    blocks = 2.0 * nnz * _BLOCK_BYTES_PER_CELL * _TILE_PAD / p
    total = slices + snapshots + mirror + blocks
    return {
        "store_slices_bytes": slices,
        "snapshot_bytes": snapshots,
        "mirror_bytes": mirror,
        "block_arrays_bytes": blocks,
        "processes": p,
        "total": total,
    }


def fits_fleet_host(num_users: int, num_movies: int, nnz: int, rank: int,
                    *, host_ram_bytes: float, dtype: str = "float32",
                    processes: int = 1, armed: bool = True) -> bool:
    """THE fleet host-RAM predicate: does one process's share of the
    out-of-core tier fit one host's RAM budget?  ``processes=1`` is the
    single-host question — the resolver's fleet provenance proves a
    shape that fails here at P=1 passes at the fleet size, which is the
    claim that makes multi-process training worth its DCN bytes."""
    need = fleet_host_ram_bytes(num_users, num_movies, nnz, rank,
                                dtype=dtype, processes=processes,
                                armed=armed)["total"]
    return need <= host_ram_bytes * RESIDENT_FRACTION
