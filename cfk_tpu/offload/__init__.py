"""Out-of-core factor tables (ROADMAP item 3 / ISSUEs 11+12).

Host-RAM-resident sharded factor stores with ``device_put``-pipelined
windows: the fixed side of each half-iteration streams through the device
one window at a time while the current window's Gram+solve runs, bit-exact
vs the resident path — single-shard (the stream-mode all_gather scan) AND
sharded (per-shard windows under the all_gather scan or the
ring/hier_ring visit schedules, with int8 (codes, scales) PCIe staging
and zero-copy window plans).  ``budget`` is the PER-SHARD memory
predicate shared with the execution planner (``plan.resolver`` resolves
oversized problems to the ``host_window`` tier through it);
``parallel.spmd.half_step_tiled_ring_hier`` is the matching resident
hierarchical ICI×DCN exchange whose visit order the windowed ring driver
replicates.  See ARCHITECTURE.md "Out-of-core factor tables".
"""

from cfk_tpu.offload.staging import (
    DEFAULT_POOL_DEPTH,
    StagingStats,
    WindowStager,
    resolve_staging,
)
from cfk_tpu.offload.store import HostFactorStore, quantize_rows_host
from cfk_tpu.offload.window import (
    RingWindowPlan,
    WindowPlan,
    build_ring_window_plan,
    build_window_plan,
)

__all__ = [
    "HostFactorStore",
    "quantize_rows_host",
    "RingWindowPlan",
    "WindowPlan",
    "build_ring_window_plan",
    "build_window_plan",
    "train_als_host_window",
    "windowed_half_step",
    "ring_windowed_half_step",
    "WindowStager",
    "StagingStats",
    "resolve_staging",
    "DEFAULT_POOL_DEPTH",
]


def __getattr__(name):
    # windowed imports jax; keep the package importable without it (the
    # budget predicate is consumed by the jax-free plan layer).
    if name in ("train_als_host_window", "windowed_half_step",
                "ring_windowed_half_step"):
        from cfk_tpu.offload import windowed

        return getattr(windowed, name)
    raise AttributeError(name)
