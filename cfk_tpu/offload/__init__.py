"""Out-of-core factor tables (ROADMAP item 3 / ISSUE 11).

Host-RAM-resident sharded factor stores with ``device_put``-pipelined
windows: the fixed side of each half-iteration streams through the device
one window at a time while the current window's Gram+solve runs, bit-exact
vs the resident path.  ``budget`` is the memory predicate shared with the
execution planner (``plan.resolver`` resolves oversized problems to the
``host_window`` tier through it); ``parallel.spmd.
half_step_tiled_ring_hier`` is the matching hierarchical ICI×DCN exchange.
See ARCHITECTURE.md "Out-of-core factor tables".
"""

from cfk_tpu.offload.store import HostFactorStore
from cfk_tpu.offload.window import WindowPlan, build_window_plan

__all__ = [
    "HostFactorStore",
    "WindowPlan",
    "build_window_plan",
    "train_als_host_window",
    "windowed_half_step",
]


def __getattr__(name):
    # windowed imports jax; keep the package importable without it (the
    # budget predicate is consumed by the jax-free plan layer).
    if name in ("train_als_host_window", "windowed_half_step"):
        from cfk_tpu.offload import windowed

        return getattr(windowed, name)
    raise AttributeError(name)
