"""Distributed window-residual exchange: the multi-process out-of-core tier.

The ALX end-state (arXiv 2112.02194): each host owns only its
entity-range ``HostFactorStore`` slice — host RAM scales out with the
fleet — and the windowed driver's per-host schedules run where they were
always headed: one process per host.  What has to move between hosts is
exactly the COLD WINDOW RESIDUAL the single-process driver already
meters as its DCN share (``_stage_table``'s fabric attribution): the
fixed-table rows a shard's windows gather from store shards other
processes own.

The protocol rides on one structural fact the out-of-core tier has
maintained since PR 10: window plans, visit schedules, and hot/delta
split maps are DETERMINISTIC functions of the tiled blocks.  Every
process builds every shard's plans identically, so the full exchange
manifest — who ships which rows to whom, in which hier-ring phase — is
computed without any communication (``build_half_exchange``), and the
wire carries only factor bytes, never indices.

Per half-iteration, per outer DCN phase ``t`` of the hier-ring visit
order (``parallel.spmd.hier_phase_of_visit`` — the SAME phase structure
``half_step_tiled_ring_hier`` rotates; ``ici_group == num_shards``
degenerates to one phase, the flat path):

- each process ships the residual rows any peer's phase-``t`` windows
  gather from its slice, CUMULATIVELY deduplicated (a row crosses DCN at
  most once per half, however many windows reference it);
- with the hot/delta engine on (ISSUE 15), manifests are built from the
  per-window COLD DELTA row sets — the hot partition and delta-kept rows
  never ship — plus one phase-0 hot-refresh manifest (the fixed side's
  remote-owned hot rows, so each process rebuilds its device partition
  from master bytes);
- payloads are the raw little-endian bytes of the store dtype (bitwise —
  no re-encode), padded to the plan-time maximum row count over
  processes (Gloo requires equal collective shapes; measured: ragged
  ``process_allgather`` shapes crash the transport), and shipped via
  ``multihost_utils.process_allgather``;
- receivers slice each peer's payload by the plan-known layout
  (``send_rows`` is sorted-unique, so selection is a searchsorted) into
  a ``ResidualMirror`` — a read-only ``HostFactorStore`` facade over
  (local slice, received residual) whose ``gather``/``shard_of_rows``
  are bitwise the full store's.  The staging pipeline, fault hooks,
  checksums, and fabric attribution then run UNCHANGED against it,
  which is what makes the 2-process drill crc-bit-identical to the
  one-process driver (``tests/test_offload_exchange.py`` pins the staged
  bytes meshless; ``tests/multihost_worker.py --drill offload`` pins the
  factors over real Gloo processes).

Accounting: ``exchange_rows_dcn``/``exchange_bytes_dcn`` meter the
pairwise residual a point-to-point DCN fabric would carry (the protocol
quantity the bench fleet row records); ``exchange_wire_bytes`` meters
what the allgather transport actually moved (pad × peers — the honest
gap between the reference collective and a tuned pairwise exchange).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cfk_tpu.offload.staging import stats_add
from cfk_tpu.offload.store import HostFactorStore, _np_dtype
from cfk_tpu.telemetry import span


def full_store_bounds(rows_total: int, num_shards: int) -> np.ndarray:
    """The shard bounds a FULL-table ``HostFactorStore`` would carry —
    the one formula (ceil-split, clipped) duplicated nowhere else: the
    mirror's ``shard_of_rows`` must be bitwise the full store's for the
    fabric attribution to survive the multi-process split."""
    per = -(-rows_total // num_shards)
    return np.minimum(np.arange(0, num_shards + 1) * per, rows_total)


@dataclasses.dataclass(frozen=True)
class OwnershipMap:
    """Which process owns which shards (and therefore store rows) of one
    side's factor table.

    Contiguous shard blocks: process ``p`` owns shards
    ``[p·spp, (p+1)·spp)`` and — because ``padded_entities = S · local``
    makes the store's ceil-split bounds coincide exactly with the shard
    solve ranges — store rows ``[p·spp·rows_per_shard, ...)``.  Solve
    write-back is therefore always process-local; only fixed-side READS
    cross the fleet, which is why the exchange ships windows residuals
    and nothing else."""

    num_shards: int
    num_processes: int
    process: int
    rows_per_shard: int

    def __post_init__(self):
        if self.num_shards % self.num_processes != 0:
            raise ValueError(
                f"num_shards={self.num_shards} must be divisible by "
                f"num_processes={self.num_processes} (contiguous "
                "shard-block ownership; run with a shard count the fleet "
                "divides)"
            )
        if not 0 <= self.process < self.num_processes:
            raise ValueError(
                f"process {self.process} outside fleet of "
                f"{self.num_processes}"
            )

    @property
    def shards_per_process(self) -> int:
        return self.num_shards // self.num_processes

    @property
    def rows_total(self) -> int:
        return self.num_shards * self.rows_per_shard

    def owner_of_shard(self, shard: int) -> int:
        return shard // self.shards_per_process

    def owned_shards(self, process: int | None = None) -> range:
        p = self.process if process is None else process
        spp = self.shards_per_process
        return range(p * spp, (p + 1) * spp)

    def row_bounds(self, process: int | None = None) -> tuple[int, int]:
        p = self.process if process is None else process
        spp_rows = self.shards_per_process * self.rows_per_shard
        return p * spp_rows, (p + 1) * spp_rows


@dataclasses.dataclass(frozen=True)
class PhaseExchange:
    """One DCN phase's manifests: ``send_rows[q]`` is the sorted-unique
    absolute rows process ``q`` ships (the union of every peer's needs
    from ``q`` this phase — the payload layout every process can derive,
    so the wire never carries indices); ``recv`` is THIS process's view:
    (peer, absolute rows taken, selection into the peer's payload)."""

    send_rows: tuple
    pad_rows: int
    recv: tuple

    @property
    def recv_row_count(self) -> int:
        return sum(int(r.shape[0]) for _, r, _ in self.recv)


@dataclasses.dataclass(frozen=True)
class HalfExchangePlan:
    """The full exchange schedule for one half-iteration (one fixed
    side), phase-structured by the hier-ring delivery contract."""

    side: str
    own: OwnershipMap
    phases: tuple
    # What shipping every window's remote rows WITH repeats would cost
    # this process (the no-split baseline): the hot/delta keep-chains are
    # what make the repeats identifiable, so cumulative dedup can ship a
    # row once per half — dense/deduped is the split's DCN cut, and at a
    # power-law shape the repeat mass concentrates exactly where the
    # references do.
    dense_rows_total: int = 0

    @property
    def num_phases(self) -> int:
        return len(self.phases)

    @property
    def recv_rows_total(self) -> int:
        return sum(p.recv_row_count for p in self.phases)

    @property
    def send_rows_total(self) -> int:
        return sum(int(p.send_rows[self.own.process].shape[0])
                   for p in self.phases)


def _phase_row_lists(own: OwnershipMap, plans, schedules, *, inner: int,
                     visits, hmaps, hot_rows):
    """``need[p][t]``: the row arrays process ``p``'s shards gather in
    phase ``t`` — delta rows under the hot/delta engine (the cold
    residual; hot and kept rows never ship), full window row sets (pads
    included — they gather too) otherwise."""
    from cfk_tpu.parallel.spmd import hier_phase_of_visit

    if visits is None:
        num_phases = 1
    else:
        num_phases = max(1, own.num_shards // max(inner, 1))
    need = [[[] for _ in range(num_phases)]
            for _ in range(own.num_processes)]
    for d in range(own.num_shards):
        p = own.owner_of_shard(d)
        plan, hmap = plans[d], (None if hmaps is None else hmaps[d])
        if visits is None:
            for w in schedules[d]:
                rows = (hmap.delta_rows[w] if hmap is not None
                        else plan.rows[w])
                need[p][0].append(np.asarray(rows, np.int64))
        else:
            for vi, sl in enumerate(visits[d]):
                t = hier_phase_of_visit(vi, inner)
                for w in plan.windows_of_slice(sl):
                    rows = (hmap.delta_rows[w] if hmap is not None
                            else plan.rows[w])
                    need[p][t].append(np.asarray(rows, np.int64))
    if hot_rows is not None and np.asarray(hot_rows).size:
        # Hot refresh: every process rebuilds the fixed side's device
        # partition from master bytes at half start, so the full hot row
        # set rides the FIRST phase (locally-owned rows are dropped by
        # the ownership filter below like any other manifest row).
        hr = np.asarray(hot_rows, np.int64)
        for p in range(own.num_processes):
            need[p][0].append(hr)
    return need, num_phases


def build_half_exchange(own: OwnershipMap, plans, schedules, *,
                        inner: int, visits=None, hmaps=None,
                        hot_rows=None, side: str = "") -> HalfExchangePlan:
    """Derive one half's exchange schedule from the (deterministic,
    everywhere-identical) window plans — no communication.

    ``plans``/``schedules`` cover ALL shards (every process builds every
    shard's plans; only its owned shards' windows ever stage).
    ``visits`` (ring sides) is the per-shard ``hier_visit_order`` —
    phase ``t`` of the exchange is outer hop ``t`` of that schedule;
    ``None`` (stream sides) is the degenerate single-phase flat path.
    ``hmaps`` (hot/delta on) switches manifests to cold-delta rows;
    ``hot_rows`` adds the fixed side's hot-refresh manifest to phase 0.
    """
    P = own.num_processes
    need, num_phases = _phase_row_lists(
        own, plans, schedules, inner=inner, visits=visits, hmaps=hmaps,
        hot_rows=hot_rows,
    )
    empty = np.zeros(0, np.int64)
    lo_p, hi_p = own.row_bounds()
    dense = 0
    for t in range(num_phases):
        for arr in need[own.process][t]:
            dense += int(((arr < lo_p) | (arr >= hi_p)).sum())
    # Per process: per-phase REMOTE rows, cumulatively deduplicated — a
    # row received in phase t is in the mirror for every later phase, so
    # it never ships twice in one half.
    recv_rows = [[empty] * num_phases for _ in range(P)]
    for p in range(P):
        lo, hi = own.row_bounds(p)
        seen = empty
        for t in range(num_phases):
            if need[p][t]:
                r = np.unique(np.concatenate(need[p][t]))
            else:
                r = empty
            r = r[(r < lo) | (r >= hi)]
            if seen.size:
                r = np.setdiff1d(r, seen, assume_unique=True)
            recv_rows[p][t] = r
            seen = np.union1d(seen, r)
    phases = []
    my_lo, my_hi = None, None
    for t in range(num_phases):
        send = []
        for q in range(P):
            qlo, qhi = own.row_bounds(q)
            owned = [rr[(rr >= qlo) & (rr < qhi)]
                     for p in range(P) if p != q
                     for rr in (recv_rows[p][t],)]
            send.append(np.unique(np.concatenate(owned))
                        if owned else empty)
        pad = max((int(s.shape[0]) for s in send), default=0)
        recv = []
        mine = recv_rows[own.process][t]
        for q in range(P):
            if q == own.process:
                continue
            qlo, qhi = own.row_bounds(q)
            take = mine[(mine >= qlo) & (mine < qhi)]
            if take.size:
                sel = np.searchsorted(send[q], take)
                recv.append((q, take, sel.astype(np.int64)))
        phases.append(PhaseExchange(send_rows=tuple(send), pad_rows=pad,
                                    recv=tuple(recv)))
    return HalfExchangePlan(side=side, own=own, phases=tuple(phases),
                            dense_rows_total=dense)


class ResidualMirror:
    """Read-only ``HostFactorStore`` facade over (local slice, received
    window residual): the object the staging pipeline gathers from in a
    multi-process run.

    ``gather`` returns bitwise what a full-table store's would (local
    rows read the slice in place; remote rows read the raw store bytes
    the owner shipped), and ``shard_of_rows`` answers with the FULL
    table's shard bounds — so ``_stage_table``'s checksums, int8
    quantization, and local/ICI/DCN fabric attribution are byte-for-byte
    the single-process driver's.  A gather of a row the exchange never
    delivered raises loudly (a protocol violation, not a silent zero)."""

    def __init__(self, store: HostFactorStore, own: OwnershipMap) -> None:
        if store.rows != own.row_bounds()[1] - own.row_bounds()[0]:
            raise ValueError(
                f"local store holds {store.rows} rows but the ownership "
                f"map assigns {own.row_bounds()} to process {own.process}"
            )
        self._store = store
        self._own = own
        self._lo, self._hi = own.row_bounds()
        self.rank = store.rank
        self.dtype = store.dtype
        self._np = _np_dtype(store.dtype)
        self.rows = own.rows_total
        self._bounds = full_store_bounds(own.rows_total, own.num_shards)
        self._r_rows = np.zeros(0, np.int64)
        self._r_vals = np.zeros((0, store.rank), self._np)

    @property
    def num_shards(self) -> int:
        return self._own.num_shards

    @property
    def resident_bytes(self) -> int:
        """What the mirror itself pins in host RAM beyond the slice —
        the per-process residual term ``budget.fleet_host_ram_bytes``
        charges."""
        return int(self._r_rows.nbytes + self._r_vals.nbytes)

    def reset(self) -> None:
        self._r_rows = np.zeros(0, np.int64)
        self._r_vals = np.zeros((0, self.rank), self._np)

    def rebind(self, store: HostFactorStore) -> None:
        """Follow the driver's store rebinding (rollback restores a
        snapshot COPY — a new object; the mirror must read the live
        slice, never a stale one)."""
        if store.rows != self._hi - self._lo:
            raise ValueError(
                f"rebind store holds {store.rows} rows, slice is "
                f"{self._hi - self._lo}"
            )
        self._store = store

    def deliver(self, rows: np.ndarray, values: np.ndarray) -> None:
        """Merge one peer's phase payload (sorted kept sorted — phases
        ship disjoint row sets by the cumulative dedup, so a merge is a
        concatenate + argsort, never a conflict resolution)."""
        rows = np.asarray(rows, np.int64)
        if not rows.size:
            return
        values = np.asarray(values)
        if values.dtype != self._np:
            raise TypeError(
                f"residual payload dtype {values.dtype} != store dtype "
                f"{self._np} (raw-byte shipping must be bitwise)"
            )
        all_rows = np.concatenate([self._r_rows, rows])
        order = np.argsort(all_rows, kind="stable")
        self._r_rows = all_rows[order]
        self._r_vals = np.concatenate([self._r_vals, values])[order]

    def shard_of_rows(self, rows: np.ndarray) -> np.ndarray:
        rows = np.asarray(rows, dtype=np.int64)
        return np.searchsorted(self._bounds, rows, side="right") - 1

    def gather(self, rows: np.ndarray) -> np.ndarray:
        rows = np.asarray(rows, dtype=np.int64)
        if rows.size and (rows.min() < 0 or rows.max() >= self.rows):
            raise IndexError(
                f"window rows outside [0, {self.rows}): "
                f"[{rows.min()}, {rows.max()}]"
            )
        out = np.empty((rows.shape[0], self.rank), dtype=self._np)
        local = (rows >= self._lo) & (rows < self._hi)
        if local.any():
            out[local] = self._store.gather(rows[local] - self._lo)
        rem = ~local
        if rem.any():
            want = rows[rem]
            idx = np.searchsorted(self._r_rows, want)
            ok = (idx < self._r_rows.shape[0])
            ok[ok] &= self._r_rows[idx[ok]] == want[ok]
            if not ok.all():
                missing = np.unique(want[~ok])[:8]
                raise KeyError(
                    f"rows {missing.tolist()} gathered but never "
                    "delivered by the window exchange (manifest/consumer "
                    "divergence — the plans are not deterministic across "
                    "processes, or a phase was skipped)"
                )
            out[rem] = np.take(self._r_vals, idx, axis=0)
        return out


class GlooFleet:
    """The live transport: the jax distributed runtime this process was
    initialized into (``parallel.mesh.initialize_distributed``), with
    ``process_allgather`` as the one collective — at fleet size 2 an
    allgather IS the pairwise exchange, and the equal-shape stacked
    layout is what Gloo's TCP pairs require (ragged shapes crash the
    transport, measured).

    jax 0.4.37's Gloo runtime cannot reform around a changed membership;
    the elastic layer (``cfk_tpu.offload.elastic``) wraps this transport
    for transient-vs-fatal classification and supports exactly the
    2-host → 1-survivor live shrink on it (the survivor needs no further
    collectives).  ``alive`` names the original pids of the current
    membership — fixed for the lifetime of a Gloo runtime."""

    def __init__(self) -> None:
        import jax

        self.num_processes = int(jax.process_count())
        self.process = int(jax.process_index())
        self.alive = tuple(range(self.num_processes))

    def allgather_bytes(self, buf: np.ndarray) -> np.ndarray:
        """[rows, width] uint8, equal shape on every process →
        [P, rows, width] stacked in process order."""
        from jax.experimental import multihost_utils

        out = multihost_utils.process_allgather(
            np.ascontiguousarray(buf, dtype=np.uint8)
        )
        return np.asarray(out)

    def allgather_i32(self, values) -> np.ndarray:
        """Small control words (trip flags, checkpoint steps) → [P, n].
        int32 on purpose: the x64-disabled jax default would silently
        downcast int64 with a warning per call."""
        from jax.experimental import multihost_utils

        vec = np.atleast_1d(np.asarray(values, dtype=np.int32))
        return np.asarray(multihost_utils.process_allgather(vec))


class LocalFleet:
    """A meshless P-process fleet simulated in ONE process (tier-1
    tests, ``tests/test_offload_exchange.py``): ``allgather_bytes``
    stacks the per-logical-process payloads the caller registers, so the
    protocol functions run byte-for-byte the Gloo path without spawning
    anything."""

    def __init__(self, num_processes: int, process: int) -> None:
        self.num_processes = int(num_processes)
        self.process = int(process)
        self.alive = tuple(range(num_processes))
        self._pending: list | None = None

    def preload(self, payloads: list) -> None:
        self._pending = [np.ascontiguousarray(p, dtype=np.uint8)
                         for p in payloads]

    def allgather_bytes(self, buf: np.ndarray) -> np.ndarray:
        if self._pending is None:
            raise RuntimeError("LocalFleet.preload(payloads) first")
        got = np.stack(self._pending)
        self._pending = None
        return got

    def allgather_i32(self, values) -> np.ndarray:
        vec = np.atleast_1d(np.asarray(values, dtype=np.int32))
        return np.tile(vec, (self.num_processes, 1))


def phase_payload(plan: HalfExchangePlan, phase: int,
                  store: HostFactorStore) -> np.ndarray:
    """This process's phase payload: its send manifest's rows gathered
    from the local slice as RAW BYTES (dtype-agnostic, bitwise — bf16
    masters ship 2 B/cell exactly as staged windows do), padded to the
    plan-time fleet maximum so the collective shape is equal everywhere."""
    ph = plan.phases[phase]
    rows = ph.send_rows[plan.own.process]
    lo, _ = plan.own.row_bounds()
    width = store.rank * _np_dtype(store.dtype).itemsize
    buf = np.zeros((ph.pad_rows, width), np.uint8)
    if rows.size:
        vals = np.ascontiguousarray(store.gather(rows - lo))
        buf[: rows.shape[0]] = vals.view(np.uint8).reshape(
            rows.shape[0], width
        )
    return buf


def deliver_phase(plan: HalfExchangePlan, phase: int,
                  gathered: np.ndarray, mirror: ResidualMirror) -> dict:
    """Slice each peer's payload by the plan-known layout into the
    mirror; returns the phase's accounting (pairwise residual rows/bytes
    + actual wire bytes)."""
    ph = plan.phases[phase]
    np_dt = _np_dtype(mirror.dtype)
    width = mirror.rank * np_dt.itemsize
    rows_got = 0
    for peer, take, sel in ph.recv:
        n = int(ph.send_rows[peer].shape[0])
        vals = np.ascontiguousarray(gathered[peer, :n]).view(
            np_dt
        ).reshape(n, mirror.rank)
        mirror.deliver(take, np.ascontiguousarray(vals[sel]))
        rows_got += int(take.shape[0])
    return {
        "rows": rows_got,
        "bytes": rows_got * width,
        "wire_bytes": int(ph.pad_rows) * width
        * (plan.own.num_processes - 1),
    }


def exchange_half(plan: HalfExchangePlan, store: HostFactorStore,
                  mirror: ResidualMirror, fleet, *, stats=None,
                  iteration: int = 0) -> dict:
    """Run one half's full exchange: reset the mirror, then one
    collective per DCN phase in visit order.  All phases complete before
    the half's compute starts (the staging pool may stage any window
    ahead of consumption, so the mirror must be whole first; overlapping
    phase t+1's collective under phase t's compute is the on-TPU
    follow-up).  Phases with an empty fleet-wide manifest skip the
    collective — a plan-time constant, so every process skips together."""
    mirror.rebind(store)
    mirror.reset()
    totals = {"rows": 0, "bytes": 0, "wire_bytes": 0}
    for t in range(plan.num_phases):
        ph = plan.phases[t]
        if ph.pad_rows == 0:
            continue
        with span("train/iter/half_step/window_exchange",
                  side=plan.side, phase=t, host=fleet.process,
                  iteration=iteration, rows=ph.recv_row_count):
            payload = phase_payload(plan, t, store)
            gathered = fleet.allgather_bytes(payload)
            got = deliver_phase(plan, t, gathered, mirror)
        for k, v in got.items():
            totals[k] += v
    if stats is not None:
        stats_add(stats, "exchange_rows_dcn", totals["rows"])
        stats_add(stats, "exchange_bytes_dcn", totals["bytes"])
        stats_add(stats, "exchange_wire_bytes", totals["wire_bytes"])
    return totals


def allgather_store(fleet, store: HostFactorStore,
                    own: OwnershipMap) -> np.ndarray:
    """Assemble the full table from every process's slice (final model
    hand-off and the drills' crc comparison; equal slice shapes by the
    divisibility contract).  At true ALX scale the full table never
    materializes on one host — callers that only need the local slice
    skip this."""
    np_dt = _np_dtype(store.dtype)
    width = store.rank * np_dt.itemsize
    flat = np.ascontiguousarray(store.as_array()).view(np.uint8).reshape(
        store.rows, width
    )
    got = fleet.allgather_bytes(flat)
    full = np.ascontiguousarray(
        got.reshape(own.num_processes * store.rows, width)
    ).view(np_dt).reshape(own.rows_total, store.rank)
    return full


def agree_min_i32(fleet, value: int) -> int:
    """Fleet-wide minimum of one int32 (checkpoint-step agreement: the
    newest step EVERY host holds intact is the only resumable one)."""
    return int(fleet.allgather_i32([int(value)]).min())


def any_flag(fleet, flag: bool) -> np.ndarray:
    """Allgather one boolean per process (the lockstep trip word: any
    host's sentinel trip rolls every host back identically)."""
    return fleet.allgather_i32([1 if flag else 0]).reshape(-1)
