"""Concurrent host staging engine (ISSUE 13).

PR 10/11 gave the out-of-core tier its SCHEDULE — per-shard windows under
the all_gather chunk scan or the ring/hier_ring visit orders — but not its
CONCURRENCY: ``train_als_host_window`` drove shards serially at the Python
level, so every shard's host-side window work (the ``stage_chunks`` view
assembly, the ``HostFactorStore`` gather, ``quantize_rows_host``, the
crc32 staging verify, and the ``device_put`` issue) sat on the one
consuming thread, and the sharded host_window wall-clock overstated the
tier (the explicit ROADMAP caveat).  ALX (arXiv 2112.02194) hides factor
streaming behind compute by pipelining transfers per shard concurrently;
this module is that pipeline's host half.

``WindowStager`` serves staged windows to the per-shard half-steps in the
EXACT consumption order each schedule commits — the driver flattens
(shard, window) tasks shard-major, each shard's windows in its own visit
order — while staging AHEAD of consumption on a bounded thread pool:

- ``mode="pool"``: up to ``depth`` tasks are in flight beyond the window
  being consumed (``depth + 1`` windows live on device — the staging
  arena ``offload/budget.py`` charges), executed by up to ``workers``
  threads.  Shard d+1's windows stage while shard d's compute runs, and a
  straggling fetch on one shard (``SlowHostFetch(only_shard=)``) blocks
  only its own future — the other workers keep staging and the consumer
  keeps draining until it actually needs the late window.
- ``mode="serial"``: the task runs on the CALLER'S thread inside
  ``take()`` — byte-for-byte the PR 10/11 double-buffer schedule (the
  half-steps call ``take()`` for window w+1 between dispatching window
  w's compute and joining it), kept as the A/B baseline arm.

Ordering/bit-exactness contract: staging is a PURE READ of the host store
(the stores are only written after a half-iteration completes), every
window is consumed in its schedule position regardless of which thread
staged it, and the compute order is untouched — so pooled and serial
staging are crc-identical to each other and to the resident shard_map
paths (``tests/test_offload_sharded.py`` pins the matrix).

Failure contract: a worker exception (a ``WindowIntegrityError`` from the
staging checksum, a chaos ``StagingCrash``, anything) propagates out of
``take()`` as the staging error — never a hang — and ``close()`` cancels
the not-yet-started tasks and drains the running ones, so a recovery
rollback never races a worker still reading the pre-rollback store.

Accounting (the bench/perf_lab staging columns):

- ``stage_busy_s``   — summed wall seconds workers (or the serial caller)
  spent inside staging tasks;
- ``stage_stall_s``  — seconds the CONSUMING thread waited in ``take()``
  for a window that was not ready: the staging time actually exposed to
  the critical path (serial mode exposes all of it by construction);
- ``pool_peak_inflight`` / ``pool_worker_stagings`` — proof the pool
  actually overlapped (the chaos straggler drill asserts on them).

``overlap_hidden_fraction = 1 - stall/busy`` is the headline column.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

from cfk_tpu.telemetry import record_event, span
from cfk_tpu.telemetry.recorder import dump_flight

# Staged-ahead windows beyond the one being consumed.  The driver clamps
# this by the window budget (depth + 1 windows must fit the staging
# share) and by the task count; 4 keeps four shards' first windows in
# flight at the default sharded shapes.
DEFAULT_POOL_DEPTH = 4
# Worker threads are capped at the depth (more could never run) and at a
# small constant — staging is memory-bound host work, and past a few
# threads the copies contend for the same bandwidth the jit compute uses.
MAX_POOL_WORKERS = 4

STAGING_MODES = ("pool", "serial")


class StagingStats(dict):
    """A stats dict with a lock: pooled staging increments shared
    counters from worker threads, and an unguarded read-modify-write
    would lose counts (``stats_add``/``stats_max`` take the lock)."""

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self.lock = threading.Lock()


def stats_add(stats, key: str, val) -> None:
    """``stats[key] += val`` — under the lock when ``stats`` carries one
    (``StagingStats``); plain dicts (single-threaded callers, tests) are
    updated directly."""
    if stats is None:
        return
    lock = getattr(stats, "lock", None)
    if lock is not None:
        with lock:
            stats[key] = stats.get(key, 0) + val
    else:
        stats[key] = stats.get(key, 0) + val


def stats_max(stats, key: str, val) -> None:
    """``stats[key] = max(stats[key], val)`` with the same locking rule."""
    if stats is None:
        return
    lock = getattr(stats, "lock", None)
    if lock is not None:
        with lock:
            stats[key] = max(stats.get(key, 0), val)
    else:
        stats[key] = max(stats.get(key, 0), val)


def resolve_staging(staging: str | None) -> str:
    """The staging mode a driver runs: an explicit pin wins, ``None``/
    ``"auto"`` resolves to the pool (the concurrency is the default
    execution mode at ANY shard count — even one shard's windows stage
    ahead across windows — like PR 1's overlap; serial is the A/B
    baseline)."""
    if staging in (None, "auto"):
        return "pool"
    if staging not in STAGING_MODES:
        raise ValueError(
            f"staging must be one of {STAGING_MODES} (or 'auto'), "
            f"got {staging!r}"
        )
    return staging


def pool_workers_for(depth: int, workers: int | None = None) -> int:
    """Worker-thread count for a pool of ``depth``: never more threads
    than windows that can be in flight, never more than the cap."""
    if workers is not None:
        return max(1, min(int(workers), max(int(depth), 1)))
    return max(1, min(int(depth), MAX_POOL_WORKERS))


class WindowStager:
    """Stage (shard, window) tasks ahead of consumption, in order.

    ``tasks`` is the flattened consumption order — the driver lists every
    shard's schedule shard-major, each shard's windows in the exact visit
    order its half-step will request them — and ``stage_fn(shard, key)``
    performs one staging (gather + quantize + verify + ``device_put``).
    ``take()`` returns the next task's staged result; the caller calls it
    exactly ``len(tasks)`` times, in order, which is what lets the pooled
    and serial modes share one consumption seam.
    """

    def __init__(self, tasks, stage_fn, *, mode: str = "pool",
                 depth: int = DEFAULT_POOL_DEPTH, workers: int | None = None,
                 stats=None, span_attrs=None) -> None:
        if mode not in STAGING_MODES:
            raise ValueError(
                f"staging mode must be one of {STAGING_MODES}, got {mode!r}"
            )
        self._tasks = list(tasks)
        self._fn = stage_fn
        self.mode = mode
        self._stats = stats
        # Optional (shard, key) -> dict of extra window_stage span attrs
        # (ISSUE 15: rows_staged / rows_delta_skipped / rows_hot — plan-
        # time constants, so the provider must be a pure lookup; it runs
        # on worker threads).
        self._span_attrs = span_attrs
        self._next_submit = 0
        self._next_take = 0
        self._closed = False
        self._pool = None
        self._futures: dict[int, object] = {}
        self._inflight = 0
        self._lock = threading.Lock()
        if mode == "pool" and self._tasks:
            self.depth = max(int(depth), 1)
            self.workers = pool_workers_for(self.depth, workers)
            self._pool = ThreadPoolExecutor(
                max_workers=self.workers, thread_name_prefix="cfk-stage",
            )
            for _ in range(min(self.depth, len(self._tasks))):
                self._submit_next()
        else:
            self.depth = 0
            self.workers = 0

    # -- internals -----------------------------------------------------------

    def _run(self, idx: int):
        shard, key = self._tasks[idx]
        with self._lock:
            self._inflight += 1
            peak = self._inflight
        stats_max(self._stats, "pool_peak_inflight", peak)
        t0 = time.perf_counter()
        try:
            # The worker span carries thread (implicit) + (shard, window)
            # ids, so pool overlap against the consuming compute spans is
            # VISIBLE in the trace; its duration is exactly the interval
            # stage_busy_s meters, which is what lets the trace-recomputed
            # overlap fraction agree with the driver's gauge.  Extra attrs
            # (rows_staged / rows_delta_skipped) come from the driver's
            # provider so the trace shows the hot/delta reuse per window.
            extra = (self._span_attrs(shard, key)
                     if self._span_attrs is not None else {})
            with span("train/iter/half_step/window_stage",
                      shard=shard, window=key, mode=self.mode, **extra):
                out = self._fn(shard, key)
        finally:
            with self._lock:
                self._inflight -= 1
        stats_add(self._stats, "stage_busy_s",
                  time.perf_counter() - t0)
        if threading.current_thread().name.startswith("cfk-stage"):
            stats_add(self._stats, "pool_worker_stagings", 1)
        return out

    def _submit_next(self) -> None:
        i = self._next_submit
        if i < len(self._tasks):
            self._futures[i] = self._pool.submit(self._run, i)
            self._next_submit += 1

    # -- the consumption seam ------------------------------------------------

    @property
    def remaining(self) -> int:
        return len(self._tasks) - self._next_take

    def take(self):
        """The next task's staged result, in task order.

        Serial mode runs the staging HERE, on the consuming thread — the
        exact schedule position the PR 10 double buffer used (the caller
        dispatches window w's compute before asking for window w+1).
        Pool mode waits on the pre-submitted future; a worker exception
        re-raises here as the staging error (after cancelling the rest),
        and the wait time is metered as the exposed staging stall."""
        i = self._next_take
        if i >= len(self._tasks):
            raise IndexError("WindowStager exhausted: every task taken")
        self._next_take += 1
        shard, key = self._tasks[i]
        if self._pool is None:
            # Serial: the whole staging occupies the consuming thread —
            # stall == busy by construction, which is what makes the
            # overlap_hidden_fraction column read 0 for the baseline arm.
            t0 = time.perf_counter()
            try:
                with span("train/iter/half_step/window_wait",
                          shard=shard, window=key, mode=self.mode):
                    out = self._run(i)
            except BaseException as e:
                record_event("fault", "staging_error", shard=shard,
                             window=key, error=f"{type(e).__name__}: {e}")
                dump_flight("staging_error")
                raise
            stats_add(self._stats, "stage_stall_s",
                      time.perf_counter() - t0)
            return out
        fut = self._futures.pop(i)
        t0 = time.perf_counter()
        try:
            with span("train/iter/half_step/window_wait",
                      shard=shard, window=key, mode=self.mode):
                out = fut.result()
        except BaseException as e:
            # Propagate as the staging error — never leave workers
            # running against a store the caller is about to roll back.
            # Flight-record first: a staging-worker death is exactly the
            # incident the ring buffer exists to explain.
            record_event("fault", "staging_error", shard=shard, window=key,
                         error=f"{type(e).__name__}: {e}")
            dump_flight("staging_error")
            self.close()
            raise
        stats_add(self._stats, "stage_stall_s",
                  time.perf_counter() - t0)
        self._submit_next()
        return out

    def close(self) -> None:
        """Cancel not-yet-started tasks and drain running ones.
        Idempotent; the driver calls it in a ``finally`` around each
        half-iteration (rollback must not race a staging worker)."""
        if self._closed:
            return
        self._closed = True
        if self._pool is not None:
            for f in self._futures.values():
                f.cancel()
            self._pool.shutdown(wait=True)
            self._futures.clear()
            self._pool = None

    def __enter__(self) -> "WindowStager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
