"""Window planning: cut a stream-mode tiled chunk scan into stageable units.

A windowed half-step runs the SAME per-chunk Gram+solve as the resident
``ops.tiled.als_half_step_tiled`` — the only difference is where the fixed
factor table lives.  The plan built here makes that literal:

- chunks are grouped into consecutive WINDOWS, cut only where
  ``carry_in == 0`` (no boundary-straddling entity crosses a cut, so each
  window's zero carry-init is exactly the resident scan's state at that
  chunk — bit-exactness needs no carry threading across host calls);
- each window's **neighbor row set** is the sorted unique table rows its
  chunks gather; the staged window is ``host_table[rows]`` and the chunk
  indices are REBASED into it (the virtual zero row F maps to the static
  ``window_rows`` slot — exactly the convention the gather kernels and the
  zero-row append already use, so the kernels run unmodified against the
  window);
- all windows share ONE static shape (``chunks_per_window`` chunks padded
  with all-trash chunks, ``window_rows`` staged rows): one jit trace
  serves every window of a side.

The builder is pure numpy on the already-built ``TiledBlocks`` arrays —
window planning is a build-time cost, paid once per dataset.

Host-memory note: the plan currently materializes padded copies of the
per-chunk arrays alongside the originals (roughly doubling the
interaction data's host footprint).  Only the REBASED neighbor stream
inherently needs new memory — rating/weight/metadata are contiguous
chunk slices that could be assembled into a reusable staging buffer at
stage time instead; that refactor is the recorded follow-up for the
true ~1B-rating regime (ROADMAP item 3 follow-ups).
"""

from __future__ import annotations

import dataclasses

import numpy as np


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


@dataclasses.dataclass(frozen=True)
class WindowPlan:
    """Per-window staged inputs of one side's windowed half-step."""

    rows: np.ndarray          # [W, R] int64 table rows staged per window
    row_counts: np.ndarray    # [W] real rows (<= R; the rest pad row 0)
    chunk_counts: np.ndarray  # [W] real chunks (<= ncw; the rest all-trash)
    neighbor_idx: np.ndarray  # [W, ncw·C] int32 window-rebased (zero row → R)
    rating: np.ndarray        # [W, ncw·C] f32
    weight: np.ndarray        # [W, ncw·C] f32
    tile_seg: np.ndarray      # [W, ncw·NT] int32
    chunk_entity: np.ndarray  # [W, ncw·Ec] int32 (trash = local_entities)
    chunk_count: np.ndarray   # [W, ncw·Ec] int32
    carry_in: np.ndarray      # [W, ncw] f32 (0 at every window start)
    last_seg: np.ndarray      # [W, ncw] int32
    statics: tuple            # (ncw, C, Ec, T) — the per-window half-step's
    window_rows: int          # R (static staged-table height)
    table_rows: int           # F (the fixed side's padded rows)
    local_entities: int       # solve side's padded rows (trash id)

    @property
    def num_windows(self) -> int:
        return int(self.rows.shape[0])

    def staged_bytes_per_window(self, rank: int, stage_itemsize: int) -> int:
        """Bytes one staged window occupies on device: the gathered table
        rows at the staging dtype plus the window's chunk arrays."""
        ncw, cap, e_c, _t = self.statics
        table = int(self.window_rows) * rank * stage_itemsize
        chunks = (
            ncw * cap * 12            # nb (int32) + rating + weight (f32)
            + self.tile_seg.shape[1] * 4
            + 2 * ncw * e_c * 4       # chunk_entity + chunk_count
            + 2 * ncw * 4             # carry_in + last_seg
        )
        return table + chunks


def build_window_plan(blocks, table_rows: int, *,
                      chunks_per_window: int = 4) -> WindowPlan:
    """Cut a stream-mode ``TiledBlocks`` side (single shard) into windows.

    ``table_rows`` is the FIXED side's padded entity count (the row space
    ``neighbor_idx`` addresses, with ``table_rows`` itself as the virtual
    zero row).  ``chunks_per_window`` is a target: a window grows past it
    when no ``carry_in == 0`` cut exists (a hot entity straddling chunks),
    and every window is padded up to the common maximum with all-trash
    chunks so one static shape serves them all.
    """
    if blocks.mode != "stream":
        raise ValueError(
            f"window plans cut the stream-mode chunk scan; these blocks "
            f"are mode={blocks.mode!r} (build with accum_max_entities=0 "
            "to force stream mode — the out-of-core regime's mode)"
        )
    if blocks.num_shards != 1:
        raise ValueError(
            "the windowed driver is single-process: build the blocks with "
            f"num_shards=1 (got {blocks.num_shards})"
        )
    if chunks_per_window < 1:
        raise ValueError(
            f"chunks_per_window must be >= 1, got {chunks_per_window}"
        )
    nc, cap, e_c, t = blocks.statics
    nt = cap // t
    nb = blocks.neighbor_idx.reshape(nc, cap)
    rt = blocks.rating.reshape(nc, cap)
    wt = blocks.weight.reshape(nc, cap)
    ts = blocks.tile_seg.reshape(nc, nt)
    ent = blocks.chunk_entity.reshape(nc, e_c)
    cnt = blocks.chunk_count.reshape(nc, e_c)
    cin = blocks.carry_in.reshape(nc)
    lseg = blocks.last_seg.reshape(nc)
    local = blocks.local_entities

    # Cut points: a window may start at chunk c only when chunk c does not
    # continue the previous chunk's last entity.
    groups: list[tuple[int, int]] = []
    start = 0
    while start < nc:
        end = min(start + chunks_per_window, nc)
        while end < nc and cin[end] != 0.0:
            end += 1
        groups.append((start, end))
        start = end

    # Floor of 2 chunks per window: a length-1 lax.scan compiles to a
    # different program shape than the same body inside a longer scan
    # (XLA simplifies away the loop), which measurably perturbs the
    # pallas-solver route's bits (~1 ulp) — an all-trash pad chunk keeps
    # every window a REAL loop with the identical body, preserving the
    # bit-exactness contract against the resident scan.  EXCEPT when the
    # resident scan itself is length-1 (nc == 1): then the single-chunk
    # window is the identical program and padding it would introduce the
    # very mismatch the floor prevents.
    ncw = max(2 if nc > 1 else 1,
              max(end - start for start, end in groups))
    f = int(table_rows)

    # Per-window unique row sets (sorted ascending — locality for the host
    # gather and a canonical rebase).
    row_lists, counts = [], []
    for lo, hi in groups:
        w_nb = nb[lo:hi].ravel()
        real = w_nb[w_nb < f]
        rows_w = np.unique(real)
        row_lists.append(rows_w)
        counts.append(rows_w.shape[0])
    window_rows = max(_round_up(max(max(counts), 1), 8), 8)

    w = len(groups)
    rows = np.zeros((w, window_rows), dtype=np.int64)
    nb_w = np.full((w, ncw * cap), window_rows, dtype=np.int32)
    rt_w = np.zeros((w, ncw * cap), dtype=np.float32)
    wt_w = np.zeros((w, ncw * cap), dtype=np.float32)
    ts_w = np.full((w, ncw * nt), e_c, dtype=np.int32)
    ent_w = np.full((w, ncw * e_c), local, dtype=np.int32)
    cnt_w = np.ones((w, ncw * e_c), dtype=blocks.chunk_count.dtype)
    cin_w = np.zeros((w, ncw), dtype=np.float32)
    lseg_w = np.zeros((w, ncw), dtype=np.int32)
    for wi, ((lo, hi), rows_w) in enumerate(zip(groups, row_lists)):
        n = hi - lo
        rows[wi, : rows_w.shape[0]] = rows_w
        chunk_nb = nb[lo:hi].ravel()
        # Rebase: real rows → their window position; the virtual zero row
        # (== f) → the window's own virtual zero row (== window_rows).
        reb = np.searchsorted(rows_w, chunk_nb).astype(np.int32)
        reb[chunk_nb >= f] = window_rows
        nb_w[wi, : n * cap] = reb
        rt_w[wi, : n * cap] = rt[lo:hi].ravel()
        wt_w[wi, : n * cap] = wt[lo:hi].ravel()
        ts_w[wi, : n * nt] = ts[lo:hi].ravel()
        ent_w[wi, : n * e_c] = ent[lo:hi].ravel()
        cnt_w[wi, : n * e_c] = cnt[lo:hi].ravel()
        cin_w[wi, :n] = cin[lo:hi]
        lseg_w[wi, :n] = lseg[lo:hi]

    return WindowPlan(
        rows=rows,
        row_counts=np.asarray(counts, dtype=np.int64),
        chunk_counts=np.asarray([hi - lo for lo, hi in groups],
                                dtype=np.int64),
        neighbor_idx=nb_w, rating=rt_w, weight=wt_w, tile_seg=ts_w,
        chunk_entity=ent_w, chunk_count=cnt_w, carry_in=cin_w,
        last_seg=lseg_w, statics=(ncw, cap, e_c, t),
        window_rows=window_rows, table_rows=f, local_entities=local,
    )
