"""Window planning: cut tiled chunk scans into stageable units.

A windowed half-step runs the SAME per-chunk Gram+solve as the resident
tiled half-steps — the only difference is where the fixed factor table
lives.  The plans built here make that literal, for both execution
shapes the resident trainers use:

- ``WindowPlan`` (stream mode, the all_gather-exchange scan): chunks are
  grouped into consecutive WINDOWS, cut only where ``carry_in == 0`` (no
  boundary-straddling entity crosses a cut, so each window's zero
  carry-init is exactly the resident scan's state at that chunk), and
  each window's **neighbor row set** is the sorted unique table rows its
  chunks gather;
- ``RingWindowPlan`` (the ring / hier-ring exchanges, accum-mode ring
  blocks): each fixed-table SLICE's chunk range is cut into windows (the
  ring's per-slice Gram accumulation is chunk-dense — no carry — so cuts
  are free), and the staged window is the slice of the neighbor rows the
  shard's chunks actually reference — the "window residual" that crosses
  PCIe/DCN instead of the whole rotating block.

In both plans the chunk indices are REBASED into the staged window (the
virtual zero row maps to the static ``window_rows`` slot — exactly the
convention the gather kernels and the zero-row append already use, so
the kernels run unmodified against the window), and all windows share
ONE static shape (``window_chunks`` chunks padded with all-trash chunks,
``window_rows`` staged rows): one jit trace serves every window of a
side.

Zero-copy contract (ISSUE 12): the plan holds ONLY the rebased neighbor
stream (which inherently needs new memory — the rebase is a new index
space) plus per-window row sets and scalar metadata.  The
rating/weight/tile/entity chunk arrays are served at stage time as
**slices of the original block arrays** (``stage_chunks`` returns numpy
VIEWS for full windows; only a ragged trailing window assembles a padded
copy, transient to the staging call).  ``plan_held_bytes`` is what a
plan pins in host RAM — roughly HALF the old padded-copy footprint,
pinned by the RSS-proxy test in ``tests/test_offload_sharded.py``.

The builders are pure numpy on the already-built ``TiledBlocks`` arrays
— window planning is a build-time cost, paid once per dataset.  Sharded
blocks (``num_shards > 1``) are planned per shard via the ``shard=``
argument: every per-shard leaf is a reshape view of the shard-major flat
arrays, so sharded planning allocates nothing beyond the per-shard
neighbor rebase.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _shard_leaf(arr: np.ndarray, num_shards: int, shard: int) -> np.ndarray:
    """Shard ``shard``'s slice of a shard-major flat block array (a VIEW)."""
    return arr.reshape(num_shards, -1)[shard]


@dataclasses.dataclass(frozen=True)
class WindowPlan:
    """Stream-mode window plan: one shard's chunk scan as staged windows.

    Plan-held arrays are the rebased neighbor stream + per-window row
    sets + tiny per-window metadata; everything else is served at stage
    time as views/assemblies over ``src`` (the original block arrays)."""

    rows: np.ndarray          # [W, R] int64 table rows staged per window
    row_counts: np.ndarray    # [W] real rows (<= R; the rest pad row 0)
    chunk_lo: np.ndarray      # [W] first source chunk of each window
    chunk_counts: np.ndarray  # [W] real chunks (<= ncw; the rest all-trash)
    neighbor_idx: np.ndarray  # [W, ncw·C] int32 window-rebased (zero row → R)
    carry_in: np.ndarray      # [W, ncw] f32 (0 at every window start)
    last_seg: np.ndarray      # [W, ncw] int32
    statics: tuple            # (ncw, C, Ec, T) — the per-window half-step's
    window_rows: int          # R (static staged-table height)
    table_rows: int           # F (the fixed side's padded rows)
    local_entities: int       # solve side's padded rows (trash id)
    # Views of the source chunk arrays (shapes [nc, cap] / [nc, nt] /
    # [nc, Ec]) — shared memory with the TiledBlocks, never copied here.
    src: dict = dataclasses.field(repr=False, default_factory=dict)

    @property
    def num_windows(self) -> int:
        return int(self.rows.shape[0])

    def schedule(self) -> list[int]:
        """The window consumption order the stream half-step commits —
        the chunk scan's own order.  THE order the staging engine
        (``offload/staging.py``) must serve windows in; having one
        authority here is what keeps the pooled and serial drivers
        consuming identical sequences."""
        return list(range(self.num_windows))

    def staged_bytes_per_window(self, rank: int, stage_itemsize: int, *,
                                row_overhead_bytes: int = 0) -> int:
        """Bytes one staged window occupies on device: the gathered table
        rows at the staging dtype (+ per-row overhead — the int8 scheme's
        f32 scale) plus the window's chunk arrays."""
        ncw, cap, e_c, t = self.statics
        nt = cap // t
        table = int(self.window_rows) * (rank * stage_itemsize
                                         + row_overhead_bytes)
        chunks = (
            ncw * cap * 12            # nb (int32) + rating + weight (f32)
            + ncw * nt * 4            # tile_seg
            + 2 * ncw * e_c * 4       # chunk_entity + chunk_count
            + 2 * ncw * 4             # carry_in + last_seg
        )
        return table + chunks

    def plan_held_bytes(self) -> int:
        """Host bytes this plan PINS for its lifetime (the zero-copy
        contract: the rebased neighbor stream + row sets + metadata; the
        chunk arrays stay the TiledBlocks' own memory)."""
        return (self.rows.nbytes + self.row_counts.nbytes
                + self.chunk_lo.nbytes + self.chunk_counts.nbytes
                + self.neighbor_idx.nbytes + self.carry_in.nbytes
                + self.last_seg.nbytes)

    def chunk_entity_of(self, w: int) -> np.ndarray:
        """Window ``w``'s [ncw·Ec] finalization rows (the host scatter's
        targets — pad chunks route to ``local_entities``)."""
        ncw, cap, e_c, t = self.statics
        n = int(self.chunk_counts[w])
        lo = int(self.chunk_lo[w])
        ent = self.src["chunk_entity"]
        if n == ncw:
            return ent[lo:lo + ncw].reshape(-1)
        out = np.full(ncw * e_c, self.local_entities, dtype=ent.dtype)
        out[: n * e_c] = ent[lo:lo + n].reshape(-1)
        return out

    def stage_chunks(self, w: int) -> tuple:
        """Window ``w``'s (rating, weight, tile_seg, chunk_entity,
        chunk_count, carry_in, last_seg) host arrays.  Full windows return
        flat VIEWS of the original block arrays (zero-copy — the whole
        point); a ragged trailing window assembles its padded copy here,
        transient to the staging call."""
        ncw, cap, e_c, t = self.statics
        nt = cap // t
        n = int(self.chunk_counts[w])
        lo = int(self.chunk_lo[w])
        s = self.src
        if n == ncw:
            return (
                s["rating"][lo:lo + ncw].reshape(-1),
                s["weight"][lo:lo + ncw].reshape(-1),
                s["tile_seg"][lo:lo + ncw].reshape(-1),
                s["chunk_entity"][lo:lo + ncw].reshape(-1),
                s["chunk_count"][lo:lo + ncw].reshape(-1),
                self.carry_in[w], self.last_seg[w],
            )
        rt = np.zeros(ncw * cap, dtype=np.float32)
        wt = np.zeros(ncw * cap, dtype=np.float32)
        ts = np.full(ncw * nt, e_c, dtype=np.int32)
        ent = np.full(ncw * e_c, self.local_entities, dtype=np.int32)
        cnt = np.ones(ncw * e_c, dtype=s["chunk_count"].dtype)
        rt[: n * cap] = s["rating"][lo:lo + n].reshape(-1)
        wt[: n * cap] = s["weight"][lo:lo + n].reshape(-1)
        ts[: n * nt] = s["tile_seg"][lo:lo + n].reshape(-1)
        ent[: n * e_c] = s["chunk_entity"][lo:lo + n].reshape(-1)
        cnt[: n * e_c] = s["chunk_count"][lo:lo + n].reshape(-1)
        return rt, wt, ts, ent, cnt, self.carry_in[w], self.last_seg[w]


def build_window_plan(blocks, table_rows: int, *,
                      chunks_per_window: int = 4,
                      shard: int = 0) -> WindowPlan:
    """Cut one shard of a stream-mode ``TiledBlocks`` side into windows.

    ``table_rows`` is the FIXED side's padded entity count (the row space
    ``neighbor_idx`` addresses, with ``table_rows`` itself as the virtual
    zero row).  ``chunks_per_window`` is a target: a window grows past it
    when no ``carry_in == 0`` cut exists (a hot entity straddling chunks),
    and every window is padded up to the common maximum with all-trash
    chunks so one static shape serves them all.  ``shard`` selects the
    shard-major slice of sharded blocks (the sharded driver builds one
    plan per shard; the per-shard chunk scan is exactly what the
    all_gather-exchange resident step runs on that shard).
    """
    if blocks.mode != "stream":
        raise ValueError(
            f"window plans cut the stream-mode chunk scan; these blocks "
            f"are mode={blocks.mode!r} (build with accum_max_entities=0 "
            "to force stream mode — the out-of-core regime's mode; the "
            "ring exchanges' accum blocks go through "
            "build_ring_window_plan)"
        )
    if not 0 <= shard < blocks.num_shards:
        raise ValueError(
            f"shard {shard} outside [0, {blocks.num_shards})"
        )
    if chunks_per_window < 1:
        raise ValueError(
            f"chunks_per_window must be >= 1, got {chunks_per_window}"
        )
    nc, cap, e_c, t = blocks.statics
    nt = cap // t
    n_sh = blocks.num_shards
    nb = _shard_leaf(blocks.neighbor_idx, n_sh, shard).reshape(nc, cap)
    rt = _shard_leaf(blocks.rating, n_sh, shard).reshape(nc, cap)
    wt = _shard_leaf(blocks.weight, n_sh, shard).reshape(nc, cap)
    ts = _shard_leaf(blocks.tile_seg, n_sh, shard).reshape(nc, nt)
    ent = _shard_leaf(blocks.chunk_entity, n_sh, shard).reshape(nc, e_c)
    cnt = _shard_leaf(blocks.chunk_count, n_sh, shard).reshape(nc, e_c)
    cin = _shard_leaf(blocks.carry_in, n_sh, shard).reshape(nc)
    lseg = _shard_leaf(blocks.last_seg, n_sh, shard).reshape(nc)
    local = blocks.local_entities

    # Cut points: a window may start at chunk c only when chunk c does not
    # continue the previous chunk's last entity.
    groups: list[tuple[int, int]] = []
    start = 0
    while start < nc:
        end = min(start + chunks_per_window, nc)
        while end < nc and cin[end] != 0.0:
            end += 1
        groups.append((start, end))
        start = end

    # Floor of 2 chunks per window: a length-1 lax.scan compiles to a
    # different program shape than the same body inside a longer scan
    # (XLA simplifies away the loop), which measurably perturbs the
    # pallas-solver route's bits (~1 ulp) — an all-trash pad chunk keeps
    # every window a REAL loop with the identical body, preserving the
    # bit-exactness contract against the resident scan.  EXCEPT when the
    # resident scan itself is length-1 (nc == 1): then the single-chunk
    # window is the identical program and padding it would introduce the
    # very mismatch the floor prevents.
    ncw = max(2 if nc > 1 else 1,
              max(end - start for start, end in groups))
    f = int(table_rows)

    # Per-window unique row sets (sorted ascending — locality for the host
    # gather and a canonical rebase).
    row_lists, counts = [], []
    for lo, hi in groups:
        w_nb = nb[lo:hi].ravel()
        real = w_nb[w_nb < f]
        rows_w = np.unique(real)
        row_lists.append(rows_w)
        counts.append(rows_w.shape[0])
    window_rows = max(_round_up(max(max(counts), 1), 8), 8)

    w = len(groups)
    rows = np.zeros((w, window_rows), dtype=np.int64)
    nb_w = np.full((w, ncw * cap), window_rows, dtype=np.int32)
    cin_w = np.zeros((w, ncw), dtype=np.float32)
    lseg_w = np.zeros((w, ncw), dtype=np.int32)
    for wi, ((lo, hi), rows_w) in enumerate(zip(groups, row_lists)):
        n = hi - lo
        rows[wi, : rows_w.shape[0]] = rows_w
        chunk_nb = nb[lo:hi].ravel()
        # Rebase: real rows → their window position; the virtual zero row
        # (== f) → the window's own virtual zero row (== window_rows).
        reb = np.searchsorted(rows_w, chunk_nb).astype(np.int32)
        reb[chunk_nb >= f] = window_rows
        nb_w[wi, : n * cap] = reb
        cin_w[wi, :n] = cin[lo:hi]
        lseg_w[wi, :n] = lseg[lo:hi]

    return WindowPlan(
        rows=rows,
        row_counts=np.asarray(counts, dtype=np.int64),
        chunk_lo=np.asarray([lo for lo, _ in groups], dtype=np.int64),
        chunk_counts=np.asarray([hi - lo for lo, hi in groups],
                                dtype=np.int64),
        neighbor_idx=nb_w, carry_in=cin_w, last_seg=lseg_w,
        statics=(ncw, cap, e_c, t),
        window_rows=window_rows, table_rows=f, local_entities=local,
        src={"rating": rt, "weight": wt, "tile_seg": ts,
             "chunk_entity": ent, "chunk_count": cnt},
    )


@dataclasses.dataclass(frozen=True)
class RingWindowPlan:
    """Ring/hier-ring window plan: one shard's accum-mode chunk scan as
    per-(fixed-table-slice) staged windows.

    The resident ring rotates whole fixed-side BLOCKS and visits each
    slice's chunk range once; windowed execution stages only the block
    rows the slice's chunks actually reference (the "window residual")
    and accumulates the identical per-chunk Grams into the shard's
    persistent [E_local+1, k(, k)] accumulator.  Cuts inside a slice are
    free (the accumulation is chunk-dense, no carry), so windows pad to
    one static shape and one jit trace serves every (slice, window).

    ``rows`` are ABSOLUTE fixed-store rows (slice·H + block-local), so
    the staging gather is one ``HostFactorStore.gather`` and the driver
    can attribute each staged row to the store shard that owns it (the
    fabric-crossing accounting the bench rows record).  Zero-copy like
    ``WindowPlan``: only the rebased neighbor stream is plan-held."""

    slice_of: np.ndarray      # [NW] int32 fixed-table slice per window
    rows: np.ndarray          # [NW, R] int64 ABSOLUTE store rows
    row_counts: np.ndarray    # [NW]
    chunk_lo: np.ndarray      # [NW] first shard-local chunk
    chunk_counts: np.ndarray  # [NW] real chunks (<= ncw)
    neighbor_idx: np.ndarray  # [NW, ncw·C] int32 rebased (zero row → R)
    statics: tuple            # the blocks' accum statics (NC, C, T, H, Ec)
    window_chunks: int        # ncw (static chunks per staged window)
    window_rows: int          # R
    local_entities: int
    num_slices: int
    src: dict = dataclasses.field(repr=False, default_factory=dict)

    @property
    def num_windows(self) -> int:
        return int(self.rows.shape[0])

    def windows_of_slice(self, t: int) -> range:
        lo = int(np.searchsorted(self.slice_of, t, side="left"))
        hi = int(np.searchsorted(self.slice_of, t, side="right"))
        return range(lo, hi)

    def schedule(self, visits: list[int]) -> list[int]:
        """The window consumption order for one shard's exchange visit
        order (``hier_visit_order``): each visited slice's windows, in
        slice-internal order — exactly the sequence the resident exchange
        delivers blocks in.  The one authority the ring half-step AND the
        staging engine share (``WindowPlan.schedule``'s ring twin)."""
        return [w for t in visits for w in self.windows_of_slice(t)]

    def staged_bytes_per_window(self, rank: int, stage_itemsize: int, *,
                                row_overhead_bytes: int = 0) -> int:
        nc, cap, t, h, e_c = self.statics
        nt = cap // t
        table = int(self.window_rows) * (rank * stage_itemsize
                                         + row_overhead_bytes)
        chunks = (self.window_chunks
                  * (cap * 12 + nt * 4 + e_c * 4))
        return table + chunks

    def plan_held_bytes(self) -> int:
        return (self.slice_of.nbytes + self.rows.nbytes
                + self.row_counts.nbytes + self.chunk_lo.nbytes
                + self.chunk_counts.nbytes + self.neighbor_idx.nbytes)

    def stage_chunks(self, w: int) -> tuple:
        """Window ``w``'s (rating, weight, tile_seg, chunk_entity) host
        arrays — views for full windows, padded assembly for ragged."""
        nc, cap, t, h, e_c = self.statics
        nt = cap // t
        ncw = self.window_chunks
        n = int(self.chunk_counts[w])
        lo = int(self.chunk_lo[w])
        s = self.src
        if n == ncw:
            return (
                s["rating"][lo:lo + ncw].reshape(-1),
                s["weight"][lo:lo + ncw].reshape(-1),
                s["tile_seg"][lo:lo + ncw].reshape(-1),
                s["chunk_entity"][lo:lo + ncw].reshape(-1),
            )
        rt = np.zeros(ncw * cap, dtype=np.float32)
        wt = np.zeros(ncw * cap, dtype=np.float32)
        ts = np.full(ncw * nt, e_c, dtype=np.int32)
        ent = np.full(ncw * e_c, self.local_entities, dtype=np.int32)
        rt[: n * cap] = s["rating"][lo:lo + n].reshape(-1)
        wt[: n * cap] = s["weight"][lo:lo + n].reshape(-1)
        ts[: n * nt] = s["tile_seg"][lo:lo + n].reshape(-1)
        ent[: n * e_c] = s["chunk_entity"][lo:lo + n].reshape(-1)
        return rt, wt, ts, ent


def build_ring_window_plan(blocks, *, shard: int,
                           chunks_per_window: int = 4) -> RingWindowPlan:
    """Cut one shard of ring-built (accum-mode) ``TiledBlocks`` into
    per-slice staged windows.

    Slices are the fixed side's factor shards (``num_slices ==
    num_shards`` for ring builds); a window never spans slices — the
    slice boundary is where the resident ring would rotate to a
    different block.  Neighbor indices are block-local in the source
    arrays; the plan rebases them to the window and records ABSOLUTE
    store rows (slice·H + local) for the staging gather."""
    if blocks.mode != "accum" or not blocks.ring:
        raise ValueError(
            "ring window plans cut ring-built accum-mode tiled blocks "
            f"(mode={blocks.mode!r}, ring={blocks.ring}); build the "
            "dataset with Dataset.from_coo(..., layout='tiled', "
            "ring=True)"
        )
    if not 0 <= shard < blocks.num_shards:
        raise ValueError(
            f"shard {shard} outside [0, {blocks.num_shards})"
        )
    if chunks_per_window < 1:
        raise ValueError(
            f"chunks_per_window must be >= 1, got {chunks_per_window}"
        )
    nc, cap, t, h, e_c = blocks.statics
    nt = cap // t
    n_sh = blocks.num_shards
    n_sl = blocks.num_slices
    nb = _shard_leaf(blocks.neighbor_idx, n_sh, shard).reshape(nc, cap)
    rt = _shard_leaf(blocks.rating, n_sh, shard).reshape(nc, cap)
    wt = _shard_leaf(blocks.weight, n_sh, shard).reshape(nc, cap)
    ts = _shard_leaf(blocks.tile_seg, n_sh, shard).reshape(nc, nt)
    ent = _shard_leaf(blocks.chunk_entity, n_sh, shard).reshape(nc, e_c)
    starts = _shard_leaf(blocks.slice_starts, n_sh, shard)
    local = blocks.local_entities

    groups: list[tuple[int, int, int]] = []  # (slice, lo, hi)
    for sl in range(n_sl):
        lo, hi = int(starts[sl]), int(starts[sl + 1])
        c = lo
        while c < hi:
            end = min(c + chunks_per_window, hi)
            groups.append((sl, c, end))
            c = end
        # An empty slice gets NO windows — the resident ring's chunk loop
        # over it is empty too (fori over an empty range); the driver's
        # windows_of_slice(t) then yields nothing for it.
    # A shard with no real chunks at all still plans (zero windows): the
    # driver's final solve runs on the zero accumulators either way,
    # matching the resident ring's empty chunk loops.
    ncw = max((hi - lo for _, lo, hi in groups), default=1)

    row_lists, counts = [], []
    for sl, lo, hi in groups:
        w_nb = nb[lo:hi].ravel()
        real = w_nb[w_nb < h]
        rows_w = np.unique(real)
        row_lists.append(rows_w)
        counts.append(rows_w.shape[0])
    window_rows = max(_round_up(max(max(counts, default=1), 1), 8), 8)

    w = len(groups)
    rows = np.zeros((w, window_rows), dtype=np.int64)
    nb_w = np.full((w, ncw * cap), window_rows, dtype=np.int32)
    for wi, ((sl, lo, hi), rows_w) in enumerate(zip(groups, row_lists)):
        n = hi - lo
        # Absolute store rows: block-local → slice base + local (pad rows
        # repeat the slice base — gathered but never referenced).
        rows[wi] = sl * h
        rows[wi, : rows_w.shape[0]] = sl * h + rows_w
        if n:
            chunk_nb = nb[lo:hi].ravel()
            reb = np.searchsorted(rows_w, chunk_nb).astype(np.int32)
            reb[chunk_nb >= h] = window_rows
            nb_w[wi, : n * cap] = reb

    return RingWindowPlan(
        slice_of=np.asarray([sl for sl, _, _ in groups], dtype=np.int32),
        rows=rows,
        row_counts=np.asarray(counts, dtype=np.int64),
        chunk_lo=np.asarray([lo for _, lo, _ in groups], dtype=np.int64),
        chunk_counts=np.asarray([hi - lo for _, lo, hi in groups],
                                dtype=np.int64),
        neighbor_idx=nb_w,
        statics=(nc, cap, t, h, e_c),
        window_chunks=ncw, window_rows=window_rows,
        local_entities=local, num_slices=n_sl,
        src={"rating": rt, "weight": wt, "tile_seg": ts,
             "chunk_entity": ent},
    )


@dataclasses.dataclass(frozen=True)
class BucketWindowPlan:
    """Bucketed-layout window plan (ISSUE 19): one side's width-class
    rectangles cut into staged windows for the implicit out-of-core path.

    A bucket is chunked exactly where the RESIDENT bucketed half-steps
    chunk it (the ``chunk_rows`` hint, which ``chunk_map`` scans), so a
    window groups consecutive resident chunks and its per-chunk batch
    shapes — hence the XLA batched-solve bits — are identical to the
    resident scan's.  Unchunked buckets stage as ONE whole-rectangle
    window (the resident path solves them in one direct call).  Windows
    never span buckets: the width class is the jit shape.

    Row sets are the FIXED-table rows a window's neighbor cells gather
    (unique over ALL cells — padding cells point at row 0 with mask 0,
    whose contribution is exactly zero, so staging their target keeps
    every rebased index in bounds without perturbing a single bit).
    ``entity`` holds each window's ABSOLUTE solve-side entity ids
    (shard·e_local + entity_local; trash rows → ``local_entities``), so
    the hot engine's helpers run with ``shard=0, local=local_entities``
    and the host scatter needs no per-shard rebase.

    Duck-typed to the ``WindowPlan`` surface the staging pipeline and
    ``offload/hot.py`` consume: rows / row_counts / window_rows /
    num_windows / schedule() / chunk_entity_of(w) / stage_chunks(w) /
    staged_bytes_per_window / plan_held_bytes."""

    rows: np.ndarray          # [W, R] int64 fixed-table rows staged per window
    row_counts: np.ndarray    # [W] real rows (<= R; the rest pad row 0)
    bucket_of: np.ndarray     # [W] int32 source bucket per window
    chunk_lo: np.ndarray      # [W] first resident chunk (bucket-local)
    chunk_counts: np.ndarray  # [W] real chunks (<= ncw; the rest all-trash)
    neighbor_idx: tuple       # per-window flat [slots·width] int32 rebased
    entity: tuple             # per-window [slots] int64 ABSOLUTE entity ids
    shapes: tuple             # per-bucket (ncw, chunk, width, whole)
    window_rows: int          # R (static staged-table height)
    table_rows: int           # F (fixed side's padded rows)
    local_entities: int       # solve side's E_pad (trash id)
    # Per-bucket {"rating": [rows, width], "mask": [rows, width]} views of
    # the Bucket arrays — shared memory, never copied here.
    src: tuple = dataclasses.field(repr=False, default_factory=tuple)

    @property
    def num_windows(self) -> int:
        return int(self.rows.shape[0])

    def schedule(self) -> list[int]:
        """Consumption order (bucket-major, chunk order within a bucket —
        the resident layout's own scan order); the one authority the
        staging engine and the half-step share."""
        return list(range(self.num_windows))

    def window_shape(self, w: int) -> tuple:
        """Window ``w``'s static solve shape (ncw, chunk, width, whole)."""
        return self.shapes[int(self.bucket_of[w])]

    def staged_bytes_per_window(self, rank: int, stage_itemsize: int, *,
                                row_overhead_bytes: int = 0) -> int:
        """Worst-case bytes one staged window occupies on device: the
        gathered table rows at the staging dtype plus the widest bucket's
        chunk arrays (nb int32 + rating f32 + mask f32 per cell, plus the
        per-slot entity ids and iALS++'s warm-start row)."""
        table = int(self.window_rows) * (rank * stage_itemsize
                                         + row_overhead_bytes)
        cells = max((ncw * chunk * width
                     for ncw, chunk, width, _ in self.shapes), default=0)
        slots = max((ncw * chunk
                     for ncw, chunk, width, _ in self.shapes), default=0)
        # entity ids (int64) + the staged warm-start row at f32 — the
        # iALS++ upper bound covers plain iALS too.
        return table + cells * 12 + slots * (8 + rank * 4)

    def plan_held_bytes(self) -> int:
        """Host bytes the plan pins (rebased neighbor stream + row sets +
        entity ids + metadata; rating/mask stay the Buckets' own memory)."""
        return (self.rows.nbytes + self.row_counts.nbytes
                + self.bucket_of.nbytes + self.chunk_lo.nbytes
                + self.chunk_counts.nbytes
                + sum(a.nbytes for a in self.neighbor_idx)
                + sum(a.nbytes for a in self.entity))

    def chunk_entity_of(self, w: int) -> np.ndarray:
        """Window ``w``'s [slots] ABSOLUTE solve-entity ids (trash →
        ``local_entities``) — the host scatter's targets and the hot
        engine's partition key."""
        return self.entity[w]

    def stage_chunks(self, w: int) -> tuple:
        """Window ``w``'s (rating, mask) flat host arrays — views for
        full windows, zero-padded assembly for the ragged trailing window
        of a chunked bucket (all-trash pad chunks: mask 0 everywhere, so
        their contribution is exactly zero)."""
        j = int(self.bucket_of[w])
        ncw, chunk, width, _whole = self.shapes[j]
        n = int(self.chunk_counts[w])
        lo = int(self.chunk_lo[w]) * chunk
        s = self.src[j]
        if n == ncw:
            hi = lo + ncw * chunk
            return (s["rating"][lo:hi].reshape(-1),
                    s["mask"][lo:hi].reshape(-1))
        rt = np.zeros(ncw * chunk * width, dtype=np.float32)
        mk = np.zeros(ncw * chunk * width, dtype=np.float32)
        real = n * chunk * width
        rt[:real] = s["rating"][lo:lo + n * chunk].reshape(-1)
        mk[:real] = s["mask"][lo:lo + n * chunk].reshape(-1)
        return rt, mk


def build_bucket_window_plan(blocks, table_rows: int, *,
                             chunks_per_window: int = 4) -> BucketWindowPlan:
    """Cut one side of a ``BucketedBlocks`` into staged windows.

    ``blocks`` is the SOLVE side (its buckets hold the rows being
    updated), ``table_rows`` the FIXED side's padded entity count (the
    row space ``neighbor_idx`` addresses).  Chunked buckets group
    ``chunks_per_window`` consecutive resident chunks per window with a
    floor of 2 (the scan-length bit contract — a length-1 ``lax.map``
    compiles to a different program than the same body in a longer scan);
    unchunked buckets stage whole, matching the resident direct solve.
    One plan covers every shard: rows are shard-major, chunk boundaries
    never straddle shards, and entity ids are absolute."""
    if chunks_per_window < 1:
        raise ValueError(
            f"chunks_per_window must be >= 1, got {chunks_per_window}"
        )
    e_local = blocks.local_entities
    e_pad = blocks.padded_entities
    n_sh = blocks.num_shards
    f = int(table_rows)

    groups = []      # (bucket j, chunk_lo, chunk_count)
    shapes = []      # per bucket (ncw, chunk, width, whole)
    src = []
    ent_abs_of = []  # per bucket [rows] int64 absolute entity ids
    for j, b in enumerate(blocks.buckets):
        rows_b, width = b.neighbor_idx.shape
        per_shard = rows_b // n_sh
        sh = np.arange(rows_b, dtype=np.int64) // per_shard
        el = b.entity_local.astype(np.int64)
        ent_abs_of.append(np.where(el < e_local, sh * e_local + el, e_pad))
        src.append({"rating": b.rating, "mask": b.mask})
        if b.chunk_rows is None or b.chunk_rows >= rows_b:
            shapes.append((1, rows_b, width, True))
            groups.append((j, 0, 1))
            continue
        chunk = int(b.chunk_rows)
        nc = rows_b // chunk  # builder guarantees chunk | rows_b, nc >= 2
        ncw = max(2, min(chunks_per_window, nc))
        shapes.append((ncw, chunk, width, False))
        c = 0
        while c < nc:
            end = min(c + ncw, nc)
            groups.append((j, c, end - c))
            c = end

    # Per-window unique row sets over ALL neighbor cells (padding cells
    # included — see class docstring), sorted ascending for gather
    # locality and a canonical rebase.
    row_lists, counts = [], []
    for j, lo, n in groups:
        _, chunk, _, _ = shapes[j]
        w_nb = blocks.buckets[j].neighbor_idx[
            lo * chunk:(lo + n) * chunk
        ].ravel()
        rows_w = np.unique(w_nb)
        row_lists.append(rows_w)
        counts.append(rows_w.shape[0])
    window_rows = max(_round_up(max(counts, default=1), 8), 8)

    w = len(groups)
    rows = np.zeros((w, window_rows), dtype=np.int64)
    nb_list, ent_list = [], []
    for wi, ((j, lo, n), rows_w) in enumerate(zip(groups, row_lists)):
        ncw, chunk, width, _whole = shapes[j]
        rows[wi, : rows_w.shape[0]] = rows_w
        slots = ncw * chunk
        chunk_nb = blocks.buckets[j].neighbor_idx[
            lo * chunk:(lo + n) * chunk
        ].ravel()
        reb = np.searchsorted(rows_w, chunk_nb).astype(np.int32)
        if n == ncw:
            nb_w = reb
            ent_w = ent_abs_of[j][lo * chunk:(lo + ncw) * chunk]
        else:
            # Ragged trailing window: all-trash pad chunks point their
            # neighbor cells at window position 0 (mask 0 — exact zero
            # contribution) and their entities at the trash slot.
            nb_w = np.zeros(slots * width, dtype=np.int32)
            nb_w[: n * chunk * width] = reb
            ent_w = np.full(slots, e_pad, dtype=np.int64)
            ent_w[: n * chunk] = ent_abs_of[j][lo * chunk:(lo + n) * chunk]
        nb_list.append(nb_w)
        ent_list.append(np.ascontiguousarray(ent_w))

    return BucketWindowPlan(
        rows=rows,
        row_counts=np.asarray(counts, dtype=np.int64),
        bucket_of=np.asarray([j for j, _, _ in groups], dtype=np.int32),
        chunk_lo=np.asarray([lo for _, lo, _ in groups], dtype=np.int64),
        chunk_counts=np.asarray([n for _, _, n in groups], dtype=np.int64),
        neighbor_idx=tuple(nb_list),
        entity=tuple(ent_list),
        shapes=tuple(shapes),
        window_rows=window_rows, table_rows=f, local_entities=e_pad,
        src=tuple(src),
    )
