"""Out-of-core training: host factor stores + windowed half-steps.

The ALX move (arXiv 2112.02194): accept that factor tables exceed one
chip's HBM, keep them in host RAM (``HostFactorStore``), and stream
WINDOWS of the fixed side through the device while the solve streams the
chunk scan.  The execution per chunk is literally the resident tiled
half-step — ``ops.tiled.als_half_step_tiled`` runs unmodified against the
staged window with rebased indices (PR 4's in-kernel gather reads from
ANY-memory-space tables, so the kernels just point at the window) — which
is what makes the windowed path BIT-EXACT vs the resident path
(``tests/test_offload.py`` pins it per knob: table dtype, gather mode,
fused epilogue, overlap).

Schedule per half-step (the ``ops/pipeline.py`` shape, one level up):

    stage(window 0)                     # host gather + device_put
    for w: stage(w+1)  ||  compute(w)   # double buffer
            scatter solved rows of w back to the host store

Window w's jitted compute is DISPATCHED first (jit dispatch is async),
then window w+1's host gather + ``device_put`` run under it, and only
then is w's result joined — so the host staging work AND the PCIe
transfer both hide under the Gram+solve exactly as the chunk pipelines
overlap their gathers; the per-window chunk math, order, and carry
semantics are unchanged (windows cut only at ``carry_in == 0``
boundaries — ``offload/window.py``).

``train_als_host_window`` is the ``offload_tier="host_window"`` executor
the planner resolves oversized problems to (``plan/resolver.py`` gates the
``device`` tier on ``offload.budget`` — the same predicate the window
sizing here consumes, so a plan can never promise a resident table that
does not fit).  Explicit ALS on the tiled stream layout, single process;
the hierarchical ICI×DCN exchange for the multi-chip regime lives in
``parallel/spmd.half_step_tiled_ring_hier``.
"""

from __future__ import annotations

import functools

import jax
import numpy as np

from cfk_tpu.config import ALSConfig
from cfk_tpu.offload import budget as _budget
# _np_dtype: the ONE validated name→numpy-dtype mapping (raises on
# anything but float32/bfloat16 — no silent fallthrough).
from cfk_tpu.offload.store import HostFactorStore, _np_dtype
from cfk_tpu.offload.window import WindowPlan, build_window_plan


def _stage_dtype(store_dtype: str, table_dtype: str | None) -> str:
    """The dtype windows cross PCIe at: bf16 tables stage bf16 (half the
    transfer — the cast is per-element, so host-cast == device-cast
    bit-exactly); int8 stages at the storage dtype and quantizes on device
    per window (per-row scheme ⇒ window quantization == sliced full-table
    quantization; staging the codes themselves is an on-TPU follow-up)."""
    if table_dtype == "bfloat16":
        return "bfloat16"
    return store_dtype


@functools.partial(
    jax.jit,
    static_argnames=("statics", "lam", "solver", "overlap",
                     "fused_epilogue", "in_kernel_gather",
                     "reg_solve_algo", "table_dtype", "out_dtype"),
)
def _window_half_jit(tbl, nb, rt, wt, ts, ent, cnt, cin, lseg, *, statics,
                     lam, solver, overlap, fused_epilogue,
                     in_kernel_gather, reg_solve_algo, table_dtype,
                     out_dtype):
    """One window's chunks through the UNMODIFIED stream-mode half-step
    (``return_chunk_rows`` skips the device scatter — the host does it)."""
    from cfk_tpu.ops.tiled import tiled_half_step

    blk = dict(neighbor_idx=nb, rating=rt, weight=wt, tile_seg=ts,
               chunk_entity=ent, chunk_count=cnt, carry_in=cin,
               last_seg=lseg)
    xs = tiled_half_step(
        tbl, blk, ("tiled", "stream") + statics, 1, lam,
        solver=solver, overlap=overlap, fused_epilogue=fused_epilogue,
        in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
        table_dtype=table_dtype, return_chunk_rows=True,
    )
    return xs.astype(jax.numpy.dtype(out_dtype))


class WindowIntegrityError(RuntimeError):
    """A staged window's bytes no longer match the host store's (torn or
    corrupted transfer, caught by the staging checksum — the window
    analog of the checkpoint crc32 contract)."""


def windowed_half_step(
    fixed_store: HostFactorStore, wplan: WindowPlan, *, lam: float,
    out_dtype: str = "float32", solver: str = "auto", overlap=None,
    fused_epilogue=None, in_kernel_gather=None, reg_solve_algo=None,
    table_dtype: str | None = None, faults=None, iteration: int = 0,
    side: str = "", stats: dict | None = None, verify_windows: bool = False,
) -> np.ndarray:
    """Solve one side against a host-resident fixed table, window by
    window.  Returns the solved [local_entities, rank] host array in
    ``out_dtype`` (untouched rows zero — exactly the resident scatter's
    output).  ``faults`` (chaos only) is a
    ``resilience.faults.WindowFaultInjector``; ``verify_windows``
    checksums each staged window at the store (crc32 before the staging
    hand-off) against what is about to ship, and raises
    ``WindowIntegrityError`` on a mismatch — NaN poisoning is caught by
    the factor sentinel either way, but a TORN window is finite-and-
    wrong, which only an integrity check can see.  Scope is the HOST
    staging pipeline up to the ``device_put`` hand-off (which is where
    the chaos fault hook models its corruption); verifying the PCIe DMA
    itself would need a device-side checksum — on-TPU follow-up."""
    import zlib

    k = fixed_store.rank
    stage_name = _stage_dtype(fixed_store.dtype, table_dtype)
    stage_np = _np_dtype(stage_name)
    out = np.zeros((wplan.local_entities, k), dtype=_np_dtype(out_dtype))
    n_w = wplan.num_windows

    def stage(w):
        if faults is not None:
            faults.delay(iteration, side, w)
        tbl = fixed_store.gather(wplan.rows[w])
        if tbl.dtype != stage_np:
            tbl = tbl.astype(stage_np)
        src_crc = zlib.crc32(tbl.tobytes()) if verify_windows else None
        # The fault hook models in-flight staging corruption: it fires
        # BETWEEN the source checksum and the device transfer.
        if faults is not None:
            tbl = faults.apply_window(iteration, side, w, tbl)
        if verify_windows and zlib.crc32(tbl.tobytes()) != src_crc:
            raise WindowIntegrityError(
                f"side {side!r} iteration {iteration} window {w}: staged "
                "bytes diverge from the host store (torn/corrupt transfer)"
            )
        host = (
            tbl, wplan.neighbor_idx[w], wplan.rating[w], wplan.weight[w],
            wplan.tile_seg[w], wplan.chunk_entity[w], wplan.chunk_count[w],
            wplan.carry_in[w], wplan.last_seg[w],
        )
        if stats is not None:
            stats["windows_staged"] = stats.get("windows_staged", 0) + 1
            # The FULL staged working set — table AND chunk arrays — the
            # same quantity the per-window budget was sized against
            # (WindowPlan.staged_bytes_per_window), so the recorded
            # arithmetic reproduces the sizing decision.
            stats["staged_bytes"] = (
                stats.get("staged_bytes", 0)
                + sum(a.nbytes for a in host)
            )
        return tuple(jax.device_put(x) for x in host)

    staged = stage(0)
    for w in range(n_w):
        # DISPATCH window w's compute first (jit dispatch is async), THEN
        # run window w+1's host gather + device_put under it, and only
        # then join w's result: both the host staging work (the store
        # fancy-index gather, the optional checksum) and the transfer
        # overlap the device compute.
        xs = _window_half_jit(
            *staged, statics=wplan.statics, lam=float(lam), solver=solver,
            overlap=overlap, fused_epilogue=fused_epilogue,
            in_kernel_gather=in_kernel_gather,
            reg_solve_algo=reg_solve_algo, table_dtype=table_dtype,
            out_dtype=out_dtype,
        )
        nxt = stage(w + 1) if w + 1 < n_w else None
        xs_np = np.asarray(xs)
        ent = wplan.chunk_entity[w]
        real = ent < wplan.local_entities
        out[ent[real]] = xs_np[real]
        staged = nxt
    return out


def _stream_blocks_for(dataset, config: ALSConfig, tile_rows: int | None):
    """The stream-mode tiled blocks the windowed driver runs on: the
    dataset's own when they already qualify (both sides stream, one
    shard), else a rebuild from the dense COO with accum mode disabled —
    accum's persistent [E, k, k] device accumulator is exactly the
    structure the out-of-core regime cannot hold."""
    from cfk_tpu.data.blocks import TiledBlocks, build_tiled_blocks

    mb, ub = dataset.movie_blocks, dataset.user_blocks
    ok = (
        isinstance(mb, TiledBlocks) and isinstance(ub, TiledBlocks)
        and mb.mode == "stream" and ub.mode == "stream"
        and mb.num_shards == 1 and ub.num_shards == 1
    )
    if ok:
        return mb, ub
    coo = dataset.coo_dense
    t = tile_rows or (mb.tile_rows if isinstance(mb, TiledBlocks) else 128)
    m_dense = coo.movie_raw.astype(np.int64)
    u_dense = coo.user_raw.astype(np.int64)
    build = functools.partial(
        build_tiled_blocks, num_shards=1, tile_rows=t,
        chunk_elems=config.chunk_cells(), accum_max_entities=0,
    )
    mb2 = build(m_dense, u_dense, coo.rating,
                dataset.movie_map.num_entities, dataset.user_map.num_entities)
    ub2 = build(u_dense, m_dense, coo.rating,
                dataset.user_map.num_entities, dataset.movie_map.num_entities)
    return mb2, ub2


def _probe(u: np.ndarray, m: np.ndarray, norm_limit: float | None) -> str | None:
    """Host-side sentinel over the solved stores: NaN/Inf anywhere, or a
    factor-row 2-norm past the watchdog limit.  Returns the trip reason or
    None (the same reason vocabulary as ``resilience.sentinel``)."""
    for name, x in (("user", u), ("movie", m)):
        xf = np.asarray(x, dtype=np.float32)
        if not np.isfinite(xf).all():
            return f"nonfinite {name} factors"
        if norm_limit is not None:
            n = float(np.sqrt((xf * xf).sum(axis=1)).max()) if xf.size else 0.0
            if n > norm_limit:
                return f"{name} row norm {n:.3g} > {norm_limit:.3g}"
    return None


def train_als_host_window(
    dataset,
    config: ALSConfig,
    *,
    metrics=None,
    window_faults=None,
    tile_rows: int | None = None,
    chunks_per_window: int | None = None,
    device_budget_bytes: float | None = None,
    plan_provenance=None,
    verify_windows: bool | None = None,
):
    """ALS-WR with host-resident factor tables and windowed half-steps.

    Same math, init, and iteration order as ``train_als`` on the same
    stream-mode tiled blocks — bit-exact at every supported knob
    (``tests/test_offload.py``).  Supports explicit ALS, ``layout='tiled'``,
    one process; divergence recovery runs the PR 3 ladder against in-RAM
    last-good snapshots of the stores (each rung is recorded with the
    loop vocabulary and as a plan transition when provenance rides along).

    ``device_budget_bytes`` bounds the staged working set (default: the
    detected device's HBM through ``offload.budget`` — the SAME predicate
    the planner gates the ``device`` tier with); ``chunks_per_window``
    overrides the derived window size.
    """
    from cfk_tpu.ops.solve import init_factors_stats
    from cfk_tpu.resilience.policy import (
        Overrides,
        TrainingDivergedError,
        policy_from_config,
    )
    from cfk_tpu.utils.metrics import Metrics

    if config.algorithm != "als":
        raise ValueError(
            f"host-window offload supports the explicit ALS optimizer; "
            f"algorithm={config.algorithm!r} (iALS needs the global YᵀY "
            "over the full fixed table — an out-of-core reduction is the "
            "documented follow-up)"
        )
    if config.num_shards != 1:
        raise ValueError(
            "the windowed driver is single-process "
            f"(num_shards={config.num_shards}); the multi-chip regime "
            "pairs it with the hierarchical ring exchange "
            "(parallel.spmd.half_step_tiled_ring_hier)"
        )
    if config.layout != "tiled":
        raise ValueError(
            f"host-window offload streams the tiled stream-mode layout; "
            f"layout={config.layout!r}"
        )
    metrics = metrics if metrics is not None else Metrics()
    with metrics.phase("window_plan"):
        mb, ub = _stream_blocks_for(dataset, config, tile_rows)
        stage_name = _stage_dtype(config.dtype, config.table_dtype)
        stage_itemsize = _np_dtype(stage_name).itemsize
        if device_budget_bytes is None:
            from cfk_tpu.plan import DeviceSpec

            device_budget_bytes = DeviceSpec.detect().hbm_bytes
        per_window_budget = _budget.window_budget_bytes(device_budget_bytes)

        def plans_for(cpw):
            m_plan = build_window_plan(mb, ub.padded_entities,
                                       chunks_per_window=cpw)
            u_plan = build_window_plan(ub, mb.padded_entities,
                                       chunks_per_window=cpw)
            return m_plan, u_plan

        cpw = chunks_per_window or 4
        while True:
            m_plan, u_plan = plans_for(cpw)
            worst = max(
                p.staged_bytes_per_window(config.rank, stage_itemsize)
                for p in (m_plan, u_plan)
            )
            if worst <= per_window_budget or cpw == 1:
                break
            cpw = max(1, cpw // 2)
        if worst > per_window_budget:
            raise ValueError(
                f"one staged window needs {worst / 1e6:.1f} MB but the "
                f"per-window budget is {per_window_budget / 1e6:.1f} MB "
                "(device_budget · RESIDENT_FRACTION / WINDOW_BUFFERS) — "
                "lower hbm_chunk_elems so single chunks fit the budget"
            )
    metrics.gauge("offload_windows_m", m_plan.num_windows)
    metrics.gauge("offload_windows_u", u_plan.num_windows)
    metrics.gauge("offload_window_rows_m", m_plan.window_rows)
    metrics.gauge("offload_window_rows_u", u_plan.window_rows)
    metrics.gauge("offload_chunks_per_window", cpw)

    # Init: identical to the resident tiled trainer (init_factors_stats at
    # the padded entity count, zero movie seed).
    key = jax.random.PRNGKey(config.seed)
    u0 = init_factors_stats(
        key, jax.numpy.asarray(ub.rating_sum), jax.numpy.asarray(ub.count),
        config.rank,
    ).astype(jax.numpy.dtype(config.dtype))
    u_store = HostFactorStore.from_array(np.asarray(u0), dtype=config.dtype)
    m_store = HostFactorStore(mb.padded_entities, config.rank,
                              dtype=config.dtype)

    policy = policy_from_config(config)
    base_ov = Overrides(lam=config.lam, fused_epilogue=config.fused_epilogue)
    ov = base_ov
    norm_limit = (config.health_norm_limit
                  if config.health_check_every is not None else None)
    probe_every = config.health_check_every or 1
    stats: dict = {}
    if verify_windows is None:
        # Checksumming every staged window costs a host pass over its
        # bytes, and its scope is the host staging pipeline up to the
        # device_put hand-off (exactly the seam the chaos fault hook
        # corrupts) — so it defaults on precisely when a fault plan is
        # armed.  It is NOT a PCIe-DMA integrity check (that needs a
        # device-side checksum; on-TPU follow-up).
        verify_windows = window_faults is not None
    half_kw = dict(
        out_dtype=config.dtype, solver=config.solver,
        overlap=bool(config.overlap),
        in_kernel_gather=config.in_kernel_gather,
        table_dtype=config.table_dtype, faults=window_faults, stats=stats,
        verify_windows=verify_windows,
    )
    # Probing + last-good snapshots cost a full host pass + memcpy over
    # both stores per cadence — at the ALX regime that is gigabytes per
    # iteration — so they arm only when something can trip: the sentinel
    # (health_check_every), the staging checksum, or a chaos fault plan.
    # Unarmed runs match the resident trainer's default (no sentinel).
    armed = (config.health_check_every is not None
             or verify_windows or window_faults is not None)

    snap = (u_store.copy(), m_store.copy()) if armed else (None, None)
    snap_iter = 0
    trips = 0
    it = 0
    degraded = False

    def trip(reason: str) -> bool:
        """Rollback + ladder climb; returns False when retries are
        exhausted (degrade — the caller breaks the loop)."""
        nonlocal u_store, m_store, it, trips, ov
        trips += 1
        metrics.incr("health_trips")
        metrics.note(f"health_trip_{trips}", f"iteration {it}: {reason}")
        if trips > policy.max_recoveries:
            detail = (
                f"recovery exhausted after {policy.max_recoveries} "
                f"trips; last: {reason}"
            )
            if policy.on_unrecoverable == "raise":
                raise TrainingDivergedError(detail)
            metrics.note("degraded", detail)
            u_store, m_store = snap
            it = snap_iter
            return False
        u_store, m_store = snap[0].copy(), snap[1].copy()
        it = snap_iter
        metrics.incr("rollbacks")
        new_ov = policy.escalate(ov, trips)
        detail = (
            f"rung {trips}: rollback to iter {snap_iter}, "
            f"lam={new_ov.lam}, fused={new_ov.fused_epilogue}, "
            f"algo={new_ov.reg_solve_algo or config.reg_solve_algo}"
        )
        if new_ov != ov:
            metrics.gauge("escalation_level", trips)
            metrics.note(f"escalation_{trips}", detail)
        ov = new_ov
        if plan_provenance is not None:
            t = plan_provenance.record_transition(
                "recovery_escalation", detail
            )
            metrics.note(f"plan_transition_{trips}", str(t))
        return True

    with metrics.phase("train"):
        while it < config.num_iterations:
            algo = ov.reg_solve_algo or config.reg_solve_algo
            try:
                m_new = windowed_half_step(
                    u_store, m_plan, lam=ov.lam,
                    fused_epilogue=ov.fused_epilogue, reg_solve_algo=algo,
                    iteration=it, side="m", **half_kw,
                )
                m_store.write_range(0, m_new)
                u_new = windowed_half_step(
                    m_store, u_plan, lam=ov.lam,
                    fused_epilogue=ov.fused_epilogue, reg_solve_algo=algo,
                    iteration=it, side="u", **half_kw,
                )
                u_store.write_range(0, u_new)
            except WindowIntegrityError as e:
                # The staging checksum caught a torn/corrupt window BEFORE
                # it reached a kernel; the store is intact, so rollback +
                # replay is exact (the stores may hold a half-written m —
                # the snapshot restore erases it).
                if not trip(f"window integrity: {e}"):
                    degraded = True
                    break
                continue
            it += 1
            metrics.incr("iterations")
            if not armed:
                continue
            if it % probe_every != 0 and it < config.num_iterations:
                continue
            reason = _probe(u_new, m_new, norm_limit)
            if reason is None:
                snap = (u_store.copy(), m_store.copy())
                snap_iter = it
                continue
            if not trip(reason):
                degraded = True
                break
    metrics.gauge("offload_windows_staged", stats.get("windows_staged", 0))
    metrics.gauge("offload_staged_mb",
                  round(stats.get("staged_bytes", 0) / 1e6, 3))
    if degraded:
        metrics.gauge("iterations_completed", snap_iter)

    from cfk_tpu.models.als import ALSModel

    return ALSModel(
        user_factors=u_store.as_array(),
        movie_factors=m_store.as_array(),
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )
