"""Out-of-core training: host factor stores + windowed half-steps.

The ALX move (arXiv 2112.02194): accept that factor tables exceed one
chip's HBM, keep them in host RAM (``HostFactorStore``), and stream
WINDOWS of the fixed side through the device while the solve streams the
chunk scan.  The execution per chunk is literally the resident tiled
half-step — ``ops.tiled.als_half_step_tiled`` (stream/all_gather mode) or
the ring schedules' per-slice chunk body (``parallel.spmd.
_make_tiled_slice_grams``'s ops, ring/hier_ring mode) run unmodified
against the staged window with rebased indices (PR 4's in-kernel gather
reads from ANY-memory-space tables, so the kernels just point at the
window) — which is what makes the windowed path BIT-EXACT vs the resident
path (``tests/test_offload.py`` + ``tests/test_offload_sharded.py`` pin it
per knob: shard count, exchange/ici_group, table dtype, gather mode, fused
epilogue, overlap).

Schedule per half-step (the ``ops/pipeline.py`` shape, one level up):

    stage(window 0)                     # host gather + device_put
    for w: stage(w+1)  ||  compute(w)   # double buffer
            scatter solved rows of w back to the host store

Window w's jitted compute is DISPATCHED first (jit dispatch is async),
then window w+1's host gather + ``device_put`` run under it — so the host
staging work AND the PCIe transfer both hide under the Gram+solve exactly
as the chunk pipelines overlap their gathers.  In the sharded ring modes
the same double buffer runs under the visit schedule's inner-ICI
rotations: window w+1 of the NEXT slice visit stages while the current
slice's Grams accumulate, and the only DCN-share traffic is each window's
row set gathered from a remote store shard — the "window residual" —
never the flat ring's O(S) full-table rotation.

Staged bytes per dtype (ISSUE 12): f32 windows stage 4 B/cell, bf16 2
(the cast is per-element, host-cast == device-cast bit-exactly), and int8
tables stage the (1-byte codes, one f32 per-row scale) pair the kernels
consume — a quarter of the f32 bytes — quantized ON THE HOST by
``store.quantize_rows_host``, whose arithmetic is pinned bit-identical to
the in-jit ``ops.quant.quantize_table`` (the per-row scheme makes a
window's rows quantize independently of the table around them).

``train_als_host_window`` is the ``offload_tier="host_window"`` executor
the planner resolves oversized problems to (``plan/resolver.py`` gates the
``device`` tier on ``offload.budget`` — the same per-shard predicate the
window sizing here consumes, so a plan can never promise a resident table
that does not fit).  Explicit ALS on the tiled layout; one process
driving all shards (each shard's windows stage against the entity-range
store shard placement a multi-host deployment would pin per host).
"""

from __future__ import annotations

import functools

import jax
import numpy as np

from cfk_tpu.config import ALSConfig
from cfk_tpu.offload import budget as _budget
# _np_dtype: the ONE validated name→numpy-dtype mapping (raises on
# anything but float32/bfloat16 — no silent fallthrough).
from cfk_tpu.offload.store import (
    HostFactorStore,
    _np_dtype,
    quantize_rows_host,
)
from cfk_tpu.offload.window import (
    RingWindowPlan,
    WindowPlan,
    build_ring_window_plan,
    build_window_plan,
)


def _stage_dtype(store_dtype: str, table_dtype: str | None) -> str:
    """The dtype windows cross PCIe at: bf16 tables stage bf16 (half the
    transfer), int8 tables stage the (int8 codes, f32 per-row scales)
    pair (a quarter — ``quantize_rows_host`` on the host side of the
    PCIe, bit-identical to the in-jit quantization the resident path
    runs); f32 stages the storage dtype."""
    if table_dtype in ("bfloat16", "int8"):
        return table_dtype
    return store_dtype


def _stage_cell_bytes(stage_name: str) -> tuple[int, int]:
    """(bytes per staged table cell, per-row overhead bytes)."""
    if stage_name == "int8":
        return 1, 4  # codes + one f32 scale per row
    return _np_dtype(stage_name).itemsize, 0


@functools.partial(
    jax.jit,
    static_argnames=("statics", "lam", "solver", "overlap",
                     "fused_epilogue", "in_kernel_gather",
                     "reg_solve_algo", "table_dtype", "out_dtype"),
)
def _window_half_jit(tbl, scale, nb, rt, wt, ts, ent, cnt, cin, lseg, *,
                     statics, lam, solver, overlap, fused_epilogue,
                     in_kernel_gather, reg_solve_algo, table_dtype,
                     out_dtype):
    """One window's chunks through the UNMODIFIED stream-mode half-step
    (``return_chunk_rows`` skips the device scatter — the host does it).

    ``scale`` is the staged int8 window's per-row dequant scale (None for
    f32/bf16 staging): the fold into the weight channel happens HERE, the
    canonical order ``quantize_tiled_operand`` applies on the resident
    path, and the codes then flow to the half-step as an
    already-quantized table (``table_dtype=None`` — quantizing again
    would be wrong)."""
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.tiled import tiled_half_step

    if scale is not None:
        wt = quant.fold_scale(wt, scale, nb)
        table_dtype = None
    blk = dict(neighbor_idx=nb, rating=rt, weight=wt, tile_seg=ts,
               chunk_entity=ent, chunk_count=cnt, carry_in=cin,
               last_seg=lseg)
    xs = tiled_half_step(
        tbl, blk, ("tiled", "stream") + statics, 1, lam,
        solver=solver, overlap=overlap, fused_epilogue=fused_epilogue,
        in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
        table_dtype=table_dtype, return_chunk_rows=True,
    )
    return xs.astype(jax.numpy.dtype(out_dtype))


@functools.partial(
    jax.jit,
    static_argnames=("statics", "backend", "gather", "int8"),
)
def _ring_window_jit(acc_a, acc_b, tbl, scale, nb, rt, wt, ts, ent, *,
                     statics, backend, gather, int8):
    """One staged ring window's chunks, accumulated into the shard's
    persistent per-entity Gram carry — op-for-op the flat/hier ring's
    per-slice chunk body (``parallel.spmd._make_tiled_slice_grams``),
    with the staged window replacing the rotated block (gathered values
    are bitwise the block rows, so the Grams — and their scatter-add
    order — are identical)."""
    import jax.numpy as jnp
    from jax import lax

    from cfk_tpu.ops import quant
    from cfk_tpu.ops.tiled import _entity_gram_chunk

    ncw, cap, t, e_c = statics
    nt = cap // t
    k = tbl.shape[-1]
    if gather == "fused":
        fz = tbl
    else:
        fz = jnp.concatenate([tbl, jnp.zeros((1, k), tbl.dtype)])

    def chunk_body(i, acc):
        a0, b0 = acc
        nb_c = lax.dynamic_slice(nb, (i * cap,), (cap,))
        rt_c = lax.dynamic_slice(rt, (i * cap,), (cap,))
        wt_c = lax.dynamic_slice(wt, (i * cap,), (cap,))
        ts_c = lax.dynamic_slice(ts, (i * nt,), (nt,))
        ent_c = lax.dynamic_slice(ent, (i * e_c,), (e_c,))
        wt_c = quant.fold_scale(wt_c, scale, nb_c)
        a, b = _entity_gram_chunk(
            fz, nb_c, wt_c, rt_c, ts_c, t, e_c + 1, backend,
            unit_weights=not int8,
            zero_appended=gather != "fused", gather=gather,
        )
        return (a0.at[ent_c].add(a[:e_c]), b0.at[ent_c].add(b[:e_c]))

    return lax.fori_loop(0, ncw, chunk_body, (acc_a, acc_b))


@functools.partial(
    jax.jit,
    static_argnames=("local", "lam", "solver", "fused_epilogue",
                     "reg_solve_algo", "out_dtype"),
)
def _ring_solve_jit(acc_a, acc_b, cnt, *, local, lam, solver,
                    fused_epilogue, reg_solve_algo, out_dtype):
    from cfk_tpu.ops.solve import regularized_solve

    x = regularized_solve(
        acc_a[:local], acc_b[:local], cnt, lam, solver,
        fused=fused_epilogue, algo=reg_solve_algo,
    )
    return x.astype(jax.numpy.dtype(out_dtype))


class WindowIntegrityError(RuntimeError):
    """A staged window's bytes no longer match the host store's (torn or
    corrupted transfer, caught by the staging checksum — the window
    analog of the checkpoint crc32 contract)."""


def hier_visit_order(num_shards: int, inner: int, shard: int) -> list[int]:
    """The slice visit order of ``parallel.spmd.half_step_tiled_ring_hier``
    for one shard: phases walk the outer (DCN) ring, inner steps walk the
    ICI ring — ``held(p, j) = ((g−p)%O)·I + (i+p−j)%I``.  ``inner ==
    num_shards`` degenerates to the flat ring's ``(shard − r) % S``
    order, which is the exchange='ring' schedule (the bit-identity the
    resident paths already pin)."""
    if inner < 1 or num_shards % inner != 0:
        raise ValueError(
            f"inner ring size {inner} must divide num_shards={num_shards}"
        )
    outer = num_shards // inner
    g, i_pos = shard // inner, shard % inner
    return [
        ((g - p) % outer) * inner + (i_pos + p - j) % inner
        for p in range(outer) for j in range(inner)
    ]


def _stage_table(fixed_store: HostFactorStore, rows: np.ndarray, *,
                 stage_np, int8: bool, faults, iteration: int, side: str,
                 window: int, shard: int, verify_windows: bool,
                 stats: dict | None, home_shard: int, ici_group: int):
    """Gather + (optionally) quantize one window's table rows on the host
    — the staging pipeline up to the ``device_put`` hand-off.

    Fault hooks and the integrity checksum run on the GATHERED rows
    (before quantization, so a NaN fault poisons the int8 scale exactly
    as the resident in-jit quantization would); the fabric attribution
    meters which store shard each row came from relative to the compute
    shard's home (local / same-ICI-group / DCN — the hier exchange's
    payload accounting)."""
    import zlib

    if faults is not None:
        faults.delay(iteration, side, window, shard=shard)
    tbl = fixed_store.gather(rows)
    if not int8 and tbl.dtype != stage_np:
        tbl = tbl.astype(stage_np)
    src_crc = zlib.crc32(tbl.tobytes()) if verify_windows else None
    # The fault hook models in-flight staging corruption: it fires
    # BETWEEN the source checksum and the device transfer.
    if faults is not None:
        tbl = faults.apply_window(iteration, side, window, tbl,
                                  shard=shard)
    if verify_windows and zlib.crc32(tbl.tobytes()) != src_crc:
        raise WindowIntegrityError(
            f"shard {shard} side {side!r} iteration {iteration} window "
            f"{window}: staged bytes diverge from the host store "
            "(torn/corrupt transfer)"
        )
    if int8:
        data, scale = quantize_rows_host(tbl)
    else:
        data, scale = tbl, None
    if stats is not None and fixed_store.num_shards > 1:
        owners = fixed_store.shard_of_rows(rows)
        home = (owners == home_shard)
        group = (owners // max(ici_group, 1)
                 == home_shard // max(ici_group, 1))
        stats["rows_local"] = stats.get("rows_local", 0) + int(home.sum())
        stats["rows_ici"] = (stats.get("rows_ici", 0)
                             + int((group & ~home).sum()))
        stats["rows_dcn"] = stats.get("rows_dcn", 0) + int((~group).sum())
    return data, scale


def _stage_window(fixed_store: HostFactorStore, plan_obj, w: int, *,
                  stage_np, int8: bool, faults, iteration: int, side: str,
                  shard: int, verify_windows: bool, stats: dict | None,
                  ici_group: int) -> tuple:
    """Stage window ``w`` of either plan kind (the stream ``WindowPlan``
    or the ``RingWindowPlan`` — both expose rows / neighbor_idx /
    stage_chunks): host gather + optional quantization + checksum via
    ``_stage_table``, staged-bytes metering, then the ``device_put``
    hand-off.  ONE copy of the metering so the bench rows recorded from
    both execution shapes can never drift apart."""
    data, scale = _stage_table(
        fixed_store, plan_obj.rows[w], stage_np=stage_np, int8=int8,
        faults=faults, iteration=iteration, side=side, window=w,
        shard=shard, verify_windows=verify_windows, stats=stats,
        home_shard=shard, ici_group=ici_group,
    )
    host = (data, scale, plan_obj.neighbor_idx[w],
            *plan_obj.stage_chunks(w))
    if stats is not None:
        stats["windows_staged"] = stats.get("windows_staged", 0) + 1
        # The FULL staged working set — table (+ int8 scales) AND chunk
        # arrays — the same quantity the per-window budget was sized
        # against (staged_bytes_per_window), so the recorded arithmetic
        # reproduces the sizing decision.  The chunk arrays are
        # zero-copy VIEWS of the block arrays on the host, but they
        # still cross PCIe per window — staged bytes meter the transfer,
        # not host allocations.  The TABLE share is metered separately:
        # it is the bytes the staging dtype levers (int8 (codes, scales)
        # ≈ ¼ of f32 — the honest per-dtype ratio the bench rows
        # record).
        stats["staged_bytes"] = (
            stats.get("staged_bytes", 0)
            + sum(a.nbytes for a in host if a is not None)
        )
        stats["staged_table_bytes"] = (
            stats.get("staged_table_bytes", 0) + data.nbytes
            + (scale.nbytes if scale is not None else 0)
        )
    return tuple(
        jax.device_put(x) if x is not None else None for x in host
    )


def windowed_half_step(
    fixed_store: HostFactorStore, wplan: WindowPlan, *, lam: float,
    out_dtype: str = "float32", solver: str = "auto", overlap=None,
    fused_epilogue=None, in_kernel_gather=None, reg_solve_algo=None,
    table_dtype: str | None = None, faults=None, iteration: int = 0,
    side: str = "", stats: dict | None = None, verify_windows: bool = False,
    shard: int = 0, ici_group: int = 1,
) -> np.ndarray:
    """Solve one shard's entities against a host-resident fixed table,
    window by window (the stream-mode / all_gather-exchange scan).
    Returns the solved [local_entities, rank] host array in ``out_dtype``
    (untouched rows zero — exactly the resident scatter's output).
    ``faults`` (chaos only) is a ``resilience.faults.WindowFaultInjector``;
    ``verify_windows`` checksums each staged window at the store (crc32
    before the staging hand-off) against what is about to ship, and
    raises ``WindowIntegrityError`` on a mismatch — NaN poisoning is
    caught by the factor sentinel either way, but a TORN window is
    finite-and-wrong, which only an integrity check can see.  Scope is
    the HOST staging pipeline up to the ``device_put`` hand-off (which is
    where the chaos fault hook models its corruption); verifying the PCIe
    DMA itself would need a device-side checksum — on-TPU follow-up."""
    k = fixed_store.rank
    stage_name = _stage_dtype(fixed_store.dtype, table_dtype)
    int8 = stage_name == "int8"
    stage_np = None if int8 else _np_dtype(stage_name)
    out = np.zeros((wplan.local_entities, k), dtype=_np_dtype(out_dtype))
    n_w = wplan.num_windows

    def stage(w):
        return _stage_window(
            fixed_store, wplan, w, stage_np=stage_np, int8=int8,
            faults=faults, iteration=iteration, side=side, shard=shard,
            verify_windows=verify_windows, stats=stats,
            ici_group=ici_group,
        )

    staged = stage(0)
    for w in range(n_w):
        # DISPATCH window w's compute first (jit dispatch is async), THEN
        # run window w+1's host gather + device_put under it, and only
        # then join w's result: both the host staging work (the store
        # fancy-index gather, the optional quantization + checksum) and
        # the transfer overlap the device compute.
        xs = _window_half_jit(
            *staged, statics=wplan.statics, lam=float(lam), solver=solver,
            overlap=overlap, fused_epilogue=fused_epilogue,
            in_kernel_gather=in_kernel_gather,
            reg_solve_algo=reg_solve_algo, table_dtype=table_dtype,
            out_dtype=out_dtype,
        )
        nxt = stage(w + 1) if w + 1 < n_w else None
        xs_np = np.asarray(xs)
        ent = wplan.chunk_entity_of(w)
        real = ent < wplan.local_entities
        out[ent[real]] = xs_np[real]
        staged = nxt
    return out


def ring_windowed_half_step(
    fixed_store: HostFactorStore, rplan: RingWindowPlan, *, lam: float,
    visits: list[int], count_local: np.ndarray, out_dtype: str = "float32",
    solver: str = "auto", overlap=None, fused_epilogue=None,
    in_kernel_gather=None, reg_solve_algo=None,
    table_dtype: str | None = None, faults=None, iteration: int = 0,
    side: str = "", stats: dict | None = None, verify_windows: bool = False,
    shard: int = 0, ici_group: int = 1,
) -> np.ndarray:
    """One shard's ring/hier-ring half-iteration against staged windows.

    ``visits`` is the slice visit order the resident exchange would
    deliver blocks in (``hier_visit_order``); per visit, the slice's
    windows stage double-buffered while the persistent per-entity Gram
    accumulator — the SAME [E_local+1, k(,k)] carry the resident ring
    holds — absorbs each window's chunk Grams.  One solve at the end.
    The staged window is the slice rows this shard's chunks actually
    reference (the window residual) — never the whole block, which is
    how the flat ring's O(S) full-table traffic disappears."""
    import jax.numpy as jnp

    from cfk_tpu.ops.tiled import (
        default_tiled_gram_backend,
        resolve_gather_mode,
    )

    k = fixed_store.rank
    nc, cap, t, h, e_c = rplan.statics
    nt = cap // t
    local = rplan.local_entities
    backend = default_tiled_gram_backend()
    gather = resolve_gather_mode(
        in_kernel_gather, backend, "full", cap, nt, t, e_c + 1, k,
    )
    stage_name = _stage_dtype(fixed_store.dtype, table_dtype)
    int8 = stage_name == "int8"
    stage_np = None if int8 else _np_dtype(stage_name)
    schedule = [w for t_idx in visits
                for w in rplan.windows_of_slice(t_idx)]

    def stage(w):
        return _stage_window(
            fixed_store, rplan, w, stage_np=stage_np, int8=int8,
            faults=faults, iteration=iteration, side=side, shard=shard,
            verify_windows=verify_windows, stats=stats,
            ici_group=ici_group,
        )

    acc_a = jnp.zeros((local + 1, k, k), jnp.float32)
    acc_b = jnp.zeros((local + 1, k), jnp.float32)
    staged = stage(schedule[0]) if schedule else None
    for i, w in enumerate(schedule):
        # Dispatch this window's accumulation (async), then stage the
        # next visit's window under it — the inner-ICI-rotation overlap
        # of the resident hier ring, one level up.
        acc_a, acc_b = _ring_window_jit(
            acc_a, acc_b, *staged,
            statics=(rplan.window_chunks, cap, t, e_c),
            backend=backend, gather=gather, int8=int8,
        )
        staged = stage(schedule[i + 1]) if i + 1 < len(schedule) else None
    x = _ring_solve_jit(
        acc_a, acc_b, jax.numpy.asarray(count_local), local=local,
        lam=float(lam), solver=solver, fused_epilogue=fused_epilogue,
        reg_solve_algo=reg_solve_algo, out_dtype=out_dtype,
    )
    return np.asarray(x)


def _resolve_side_modes(dataset, config: ALSConfig
                        ) -> tuple[bool, bool]:
    """(movie_side_ring, user_side_ring) — which execution shape each
    half runs, mirroring the resident trainer's resolution EXACTLY: the
    ring exchanges apply only at num_shards > 1 (a single-device trainer
    never consults the exchange knob), ``exchange='auto'`` takes each
    half's ring flag AS BUILT (the resident per-side memory optimum,
    ``spmd.gathered_layout_trees``), and the explicit exchanges require
    matching blocks (validated by ``_blocks_for``)."""
    from cfk_tpu.data.blocks import TiledBlocks

    if config.num_shards == 1 or config.exchange == "all_gather":
        return False, False
    if config.exchange in ("ring", "hier_ring"):
        return True, True
    # exchange == "auto": per-side, from how the blocks were built.
    mb, ub = dataset.movie_blocks, dataset.user_blocks
    return (
        bool(isinstance(mb, TiledBlocks) and mb.ring),
        bool(isinstance(ub, TiledBlocks) and ub.ring),
    )


def _blocks_for(dataset, config: ALSConfig, tile_rows: int | None,
                ring_m: bool, ring_u: bool):
    """The tiled blocks the windowed driver runs on, per side.

    Stream (all_gather-shape) sides need stream mode at the config's
    shard count — the dataset's own blocks when they qualify, else a
    rebuild from the dense COO with accum mode disabled (accum's
    persistent [E, k, k] device accumulator is exactly the structure the
    out-of-core regime cannot hold).  Ring sides need the dataset's
    ring-built accum blocks as-is (their slice structure IS the exchange
    schedule; no rebuild can synthesize it honestly).  Mismatches raise
    with the same remedies the resident trainer gives."""
    from cfk_tpu.data.blocks import TiledBlocks, build_tiled_blocks

    s = config.num_shards
    mb, ub = dataset.movie_blocks, dataset.user_blocks

    def side_ok(blocks, ring):
        if not isinstance(blocks, TiledBlocks) or blocks.num_shards != s:
            return False
        if ring:
            return blocks.mode == "accum" and blocks.ring
        return blocks.mode == "stream" and not blocks.ring

    rebuilt = None

    def stream_rebuild():
        nonlocal rebuilt
        if rebuilt is None:
            coo = dataset.coo_dense
            t = tile_rows or (mb.tile_rows
                              if isinstance(mb, TiledBlocks) else 128)
            build = functools.partial(
                build_tiled_blocks, num_shards=s, tile_rows=t,
                chunk_elems=config.chunk_cells(), accum_max_entities=0,
            )
            m_dense = coo.movie_raw.astype(np.int64)
            u_dense = coo.user_raw.astype(np.int64)
            rebuilt = (
                build(m_dense, u_dense, coo.rating,
                      dataset.movie_map.num_entities,
                      dataset.user_map.num_entities),
                build(u_dense, m_dense, coo.rating,
                      dataset.user_map.num_entities,
                      dataset.movie_map.num_entities),
            )
        return rebuilt

    sides = (("movie", mb, ring_m, 0), ("user", ub, ring_u, 1))
    # Validate first: mismatches that cannot be rebuilt raise with the
    # resident trainer's own remedies.
    for name, blocks, ring, _ in sides:
        if ring and not side_ok(blocks, True):
            # Ring blocks cannot be synthesized here — their slice
            # structure IS the exchange schedule.
            raise ValueError(
                f"exchange={config.exchange!r} windowed training runs "
                f"the {name} half on ring-built tiled blocks at "
                f"num_shards={s}; rebuild with Dataset.from_coo(..., "
                f"layout='tiled', num_shards={s}, ring=True)"
            )
        if (not ring and isinstance(blocks, TiledBlocks) and blocks.ring):
            # Mirror the resident trainer: an all_gather half on
            # ring-built blocks raises there too — silently rebuilding
            # would train a different exchange schedule than the
            # resident path the bit-exactness contract compares against.
            raise ValueError(
                f"exchange={config.exchange!r} runs the {name} half as "
                "a stream scan, but its blocks were ring-built; pass "
                "exchange='ring'/'hier_ring' (the windowed ring driver) "
                "or rebuild with ring=False"
            )
    # If ANY stream side needs the rebuild, rebuild EVERY stream side:
    # mixing dataset-built and driver-rebuilt stream blocks could differ
    # in chunking (the dataset's build parameters vs the config's), and
    # one consistent build is the PR 10 discipline.
    rebuild_streams = any(
        not ring and not side_ok(blocks, False)
        for _, blocks, ring, _ in sides
    )
    out = [
        stream_rebuild()[idx] if (not ring and rebuild_streams)
        else blocks
        for _, blocks, ring, idx in sides
    ]
    return out[0], out[1]


def _probe(u: np.ndarray, m: np.ndarray, norm_limit: float | None) -> str | None:
    """Host-side sentinel over the solved stores: NaN/Inf anywhere, or a
    factor-row 2-norm past the watchdog limit.  Returns the trip reason or
    None (the same reason vocabulary as ``resilience.sentinel``)."""
    for name, x in (("user", u), ("movie", m)):
        xf = np.asarray(x, dtype=np.float32)
        if not np.isfinite(xf).all():
            return f"nonfinite {name} factors"
        if norm_limit is not None:
            n = float(np.sqrt((xf * xf).sum(axis=1)).max()) if xf.size else 0.0
            if n > norm_limit:
                return f"{name} row norm {n:.3g} > {norm_limit:.3g}"
    return None


def resolve_window_inner(config: ALSConfig) -> int:
    """The windowed driver's inner-ring size: the SAME resolution the
    resident hier ring uses (``parallel.spmd.resolve_ici_group``) for
    ``hier_ring`` — visit order must match the exchange being replaced —
    and one flat ring otherwise."""
    if config.exchange == "hier_ring":
        from cfk_tpu.parallel.spmd import resolve_ici_group

        return resolve_ici_group(config)
    return config.num_shards


def train_als_host_window(
    dataset,
    config: ALSConfig,
    *,
    metrics=None,
    window_faults=None,
    tile_rows: int | None = None,
    chunks_per_window: int | None = None,
    device_budget_bytes: float | None = None,
    plan_provenance=None,
    verify_windows: bool | None = None,
):
    """ALS-WR with host-resident factor tables and windowed half-steps.

    Same math, init, and iteration order as ``train_als`` (one shard) or
    ``parallel.spmd.train_als_sharded`` (sharded — all_gather, ring, or
    hier_ring exchange) on the same tiled blocks — bit-exact at every
    supported knob (``tests/test_offload.py`` /
    ``tests/test_offload_sharded.py``).  Explicit ALS, ``layout='tiled'``,
    ONE PROCESS driving every shard (the per-shard staging/visit
    schedules are exactly what a multi-host deployment runs per host;
    wiring them across real processes is the on-TPU backlog's job);
    divergence recovery runs the PR 3 ladder against in-RAM last-good
    snapshots of the stores (each rung is recorded with the loop
    vocabulary and as a plan transition when provenance rides along).

    ``device_budget_bytes`` bounds the staged working set PER SHARD
    (default: the detected device's HBM through ``offload.budget`` — the
    SAME predicate the planner gates the ``device`` tier with);
    ``chunks_per_window`` overrides the derived window size.
    """
    from cfk_tpu.ops.solve import init_factors_stats
    from cfk_tpu.resilience.policy import (
        Overrides,
        TrainingDivergedError,
        policy_from_config,
    )
    from cfk_tpu.utils.metrics import Metrics

    if config.algorithm != "als":
        raise ValueError(
            f"host-window offload supports the explicit ALS optimizer; "
            f"algorithm={config.algorithm!r} (iALS needs the global YᵀY "
            "over the full fixed table — an out-of-core reduction is the "
            "documented follow-up)"
        )
    if config.layout != "tiled":
        raise ValueError(
            f"host-window offload streams the tiled layout; "
            f"layout={config.layout!r}"
        )
    if jax.process_count() > 1:
        raise NotImplementedError(
            "the windowed driver runs one process driving all shards; "
            "true multi-process windowed training (per-host stores + "
            "DCN window exchange) is the on-TPU follow-up (ROADMAP)"
        )
    s = config.num_shards
    ring_m, ring_u = _resolve_side_modes(dataset, config)
    any_ring = ring_m or ring_u
    inner = resolve_window_inner(config) if any_ring else max(s, 1)
    metrics = metrics if metrics is not None else Metrics()
    with metrics.phase("window_plan"):
        mb, ub = _blocks_for(dataset, config, tile_rows, ring_m, ring_u)
        stage_name = _stage_dtype(config.dtype, config.table_dtype)
        cell_bytes, row_overhead = _stage_cell_bytes(stage_name)
        if device_budget_bytes is None:
            from cfk_tpu.plan import DeviceSpec

            device_budget_bytes = DeviceSpec.detect().hbm_bytes
        # The ring modes hold a persistent per-shard Gram accumulator
        # next to the staged windows; reserve it (×2: the dispatch
        # boundary keeps a window call's input AND output accumulators
        # alive — buffer donation is the on-TPU lever to reclaim one)
        # before splitting the remainder across the window double buffer.
        acc_reserved = 0.0
        for blocks, ring in ((mb, ring_m), (ub, ring_u)):
            if ring:
                acc_reserved = max(
                    acc_reserved,
                    2.0 * _budget.ring_accumulator_bytes(
                        blocks.local_entities, config.rank
                    ),
                )
        per_window_budget = _budget.window_budget_bytes(
            device_budget_bytes, reserved_bytes=acc_reserved
        )

        def side_plans(blocks, fixed, ring, cpw):
            if ring:
                return [build_ring_window_plan(blocks, shard=d,
                                               chunks_per_window=cpw)
                        for d in range(s)]
            return [build_window_plan(blocks, fixed.padded_entities,
                                      chunks_per_window=cpw, shard=d)
                    for d in range(s)]

        def plans_for(cpw):
            return (side_plans(mb, ub, ring_m, cpw),
                    side_plans(ub, mb, ring_u, cpw))

        cpw = chunks_per_window or 4
        while True:
            m_plans, u_plans = plans_for(cpw)
            worst = max(
                p.staged_bytes_per_window(config.rank, cell_bytes,
                                          row_overhead_bytes=row_overhead)
                for p in (*m_plans, *u_plans)
            )
            if worst <= per_window_budget or cpw == 1:
                break
            cpw = max(1, cpw // 2)
        if worst > per_window_budget:
            raise ValueError(
                f"one staged window needs {worst / 1e6:.1f} MB but the "
                f"per-window budget is {per_window_budget / 1e6:.1f} MB "
                "((device_budget · RESIDENT_FRACTION − ring accumulator "
                "reserve) / WINDOW_BUFFERS) — lower hbm_chunk_elems so "
                "single chunks fit the budget"
            )
    metrics.gauge("offload_windows_m",
                  sum(p.num_windows for p in m_plans))
    metrics.gauge("offload_windows_u",
                  sum(p.num_windows for p in u_plans))
    metrics.gauge("offload_window_rows_m",
                  max(p.window_rows for p in m_plans))
    metrics.gauge("offload_window_rows_u",
                  max(p.window_rows for p in u_plans))
    metrics.gauge("offload_chunks_per_window", cpw)
    metrics.gauge("offload_shards", s)
    metrics.gauge(
        "offload_plan_held_mb",
        round(sum(p.plan_held_bytes()
                  for p in (*m_plans, *u_plans)) / 1e6, 3),
    )
    if any_ring:
        metrics.gauge("offload_ici_group", inner)
        metrics.gauge("offload_acc_reserved_mb",
                      round(acc_reserved / 1e6, 3))
        metrics.note("offload_exchange", config.exchange)

    # Init: identical to the resident trainers (init_factors_stats drawn
    # at the REAL entity count — the shard-count-invariant init — zero
    # movie seed).
    key = jax.random.PRNGKey(config.seed)
    u0 = jax.jit(
        init_factors_stats, static_argnames=("rank", "num_entities")
    )(
        key, jax.numpy.asarray(ub.rating_sum), jax.numpy.asarray(ub.count),
        rank=config.rank, num_entities=ub.num_entities,
    ).astype(jax.numpy.dtype(config.dtype))
    u_store = HostFactorStore.from_array(np.asarray(u0), dtype=config.dtype,
                                         num_shards=s)
    m_store = HostFactorStore(mb.padded_entities, config.rank,
                              dtype=config.dtype, num_shards=s)

    policy = policy_from_config(config)
    base_ov = Overrides(lam=config.lam, fused_epilogue=config.fused_epilogue)
    ov = base_ov
    norm_limit = (config.health_norm_limit
                  if config.health_check_every is not None else None)
    probe_every = config.health_check_every or 1
    stats: dict = {}
    if verify_windows is None:
        # Checksumming every staged window costs a host pass over its
        # bytes, and its scope is the host staging pipeline up to the
        # device_put hand-off (exactly the seam the chaos fault hook
        # corrupts) — so it defaults on precisely when a fault plan is
        # armed.  It is NOT a PCIe-DMA integrity check (that needs a
        # device-side checksum; on-TPU follow-up).
        verify_windows = window_faults is not None
    half_kw = dict(
        out_dtype=config.dtype, solver=config.solver,
        overlap=bool(config.overlap),
        in_kernel_gather=config.in_kernel_gather,
        table_dtype=config.table_dtype, faults=window_faults, stats=stats,
        verify_windows=verify_windows, ici_group=inner,
    )
    m_local = mb.local_entities
    u_local = ub.local_entities
    count_m = mb.count.reshape(s, -1)
    count_u = ub.count.reshape(s, -1)

    def half(side, fixed_store, plans, local, counts, it, ring):
        """One half-iteration across every shard: per-shard windowed
        scans against the shared host store, in this side's execution
        shape (``ring`` — the per-side resolution of
        ``_resolve_side_modes``, so an ``exchange='auto'`` mixed build
        runs each half exactly as the resident trainer would).  Reads
        one store, writes a host buffer (committed by the caller) — no
        read-after-write hazard across shards, matching the resident
        step's solve-all-then-exchange structure."""
        algo = ov.reg_solve_algo or config.reg_solve_algo
        out = np.zeros((local * s, config.rank),
                       dtype=_np_dtype(config.dtype))
        for d in range(s):
            kw = dict(half_kw, lam=ov.lam,
                      fused_epilogue=ov.fused_epilogue,
                      reg_solve_algo=algo, iteration=it, side=side,
                      shard=d)
            if ring:
                visits = hier_visit_order(s, inner, d)
                rows = ring_windowed_half_step(
                    fixed_store, plans[d], visits=visits,
                    count_local=counts[d], **kw,
                )
            else:
                rows = windowed_half_step(fixed_store, plans[d], **kw)
            out[d * local:(d + 1) * local] = rows
        return out

    # Probing + last-good snapshots cost a full host pass + memcpy over
    # both stores per cadence — at the ALX regime that is gigabytes per
    # iteration — so they arm only when something can trip: the sentinel
    # (health_check_every), the staging checksum, or a chaos fault plan.
    # Unarmed runs match the resident trainer's default (no sentinel).
    armed = (config.health_check_every is not None
             or verify_windows or window_faults is not None)

    snap = (u_store.copy(), m_store.copy()) if armed else (None, None)
    snap_iter = 0
    trips = 0
    it = 0
    degraded = False

    def trip(reason: str) -> bool:
        """Rollback + ladder climb; returns False when retries are
        exhausted (degrade — the caller breaks the loop)."""
        nonlocal u_store, m_store, it, trips, ov
        trips += 1
        metrics.incr("health_trips")
        metrics.note(f"health_trip_{trips}", f"iteration {it}: {reason}")
        if trips > policy.max_recoveries:
            detail = (
                f"recovery exhausted after {policy.max_recoveries} "
                f"trips; last: {reason}"
            )
            if policy.on_unrecoverable == "raise":
                raise TrainingDivergedError(detail)
            metrics.note("degraded", detail)
            u_store, m_store = snap
            it = snap_iter
            return False
        u_store, m_store = snap[0].copy(), snap[1].copy()
        it = snap_iter
        metrics.incr("rollbacks")
        new_ov = policy.escalate(ov, trips)
        detail = (
            f"rung {trips}: rollback to iter {snap_iter}, "
            f"lam={new_ov.lam}, fused={new_ov.fused_epilogue}, "
            f"algo={new_ov.reg_solve_algo or config.reg_solve_algo}"
        )
        if new_ov != ov:
            metrics.gauge("escalation_level", trips)
            metrics.note(f"escalation_{trips}", detail)
        ov = new_ov
        if plan_provenance is not None:
            t = plan_provenance.record_transition(
                "recovery_escalation", detail
            )
            metrics.note(f"plan_transition_{trips}", str(t))
        return True

    with metrics.phase("train"):
        while it < config.num_iterations:
            try:
                m_new = half("m", u_store, m_plans, m_local, count_m, it,
                             ring_m)
                m_store.write_range(0, m_new)
                u_new = half("u", m_store, u_plans, u_local, count_u, it,
                             ring_u)
                u_store.write_range(0, u_new)
            except WindowIntegrityError as e:
                # The staging checksum caught a torn/corrupt window BEFORE
                # it reached a kernel; the store is intact, so rollback +
                # replay is exact (the stores may hold a half-written m —
                # the snapshot restore erases it).
                if not trip(f"window integrity: {e}"):
                    degraded = True
                    break
                continue
            it += 1
            metrics.incr("iterations")
            if not armed:
                continue
            if it % probe_every != 0 and it < config.num_iterations:
                continue
            reason = _probe(u_new, m_new, norm_limit)
            if reason is None:
                snap = (u_store.copy(), m_store.copy())
                snap_iter = it
                continue
            if not trip(reason):
                degraded = True
                break
    metrics.gauge("offload_windows_staged", stats.get("windows_staged", 0))
    metrics.gauge("offload_staged_mb",
                  round(stats.get("staged_bytes", 0) / 1e6, 3))
    metrics.gauge("offload_staged_table_mb",
                  round(stats.get("staged_table_bytes", 0) / 1e6, 3))
    for key_ in ("rows_local", "rows_ici", "rows_dcn"):
        if key_ in stats:
            metrics.gauge(f"offload_{key_}", stats[key_])
    if degraded:
        metrics.gauge("iterations_completed", snap_iter)

    from cfk_tpu.models.als import ALSModel

    return ALSModel(
        user_factors=u_store.as_array(),
        movie_factors=m_store.as_array(),
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )
